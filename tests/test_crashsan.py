"""crashsan + common/durable: the durable-write shapes and their crash
recovery contracts (r21).

Four layers, bottom-up:

1. The durable primitives themselves — atomic_publish/append_durable
   round-trips, thread-unique temp names, short-write loudness, the
   torn-tail-vs-mid-file-garbage split in read_wal.
2. crashsan semantics — record() enumeration, crash_at's relative
   countdown, the GRAFT_CRASHSAN gate (arming with the sanitizer off must
   fail loud, not silently never crash).
3. Per-mode on-disk crash states — each crash mode produces exactly the
   state a real death leaves, and the matching tolerant reader lands in
   its contract class.
4. The matrix — tools/crashsan_matrix.py's full sweep in-process (every
   scenario x op x mode recovers), plus the r18 "membership record in
   neither file" regression as a named crash point.

Plus the chaos-grammar end: ``torn_write:file=<durable>,op=N`` parse
checks and an end-to-end fire through a real atomic_publish.

conftest.py arms GRAFT_CRASHSAN=1 for the whole suite; these tests rely
on it (crash_at refuses to arm otherwise).
"""

import json
import os
import threading

import pytest

from elasticdl_tpu.chaos import inject as chaos
from elasticdl_tpu.common import crashsan, durable


@pytest.fixture(autouse=True)
def _clean_sanitizer():
    """Counters and per-file op indexes are process-global (that is what
    lets the chaos grammar address 'the Nth op on that file' across a real
    process lifetime) — so every test starts from zero and leaves no armed
    crash or chaos plan behind."""
    crashsan.reset()
    yield
    chaos.configure("")
    crashsan.reset()


# -- 1. durable primitives -------------------------------------------------


def test_atomic_publish_roundtrip(tmp_path):
    p = str(tmp_path / "state.json")
    durable.atomic_publish_json(p, {"v": 1})
    durable.atomic_publish_json(p, {"v": 2})
    assert durable.read_json_tolerant(p) == {"v": 2}
    # the commit leaves no stray temp behind
    assert [f for f in os.listdir(tmp_path) if ".tmp" in f] == []


def test_tmp_path_is_thread_unique(tmp_path):
    p = str(tmp_path / "f")
    names = []

    def grab():
        names.append(durable.tmp_path(p))

    t = threading.Thread(target=grab)
    t.start()
    t.join()
    grab()
    assert len(set(names)) == 2  # same pid, different tid
    assert all(f".tmp{os.getpid()}." in n for n in names)


def test_append_short_write_fails_loud(tmp_path, monkeypatch):
    """A cut-short os.write must raise ShortWriteError, not finish the
    line; the torn prefix on disk then reads as a tolerated crash tail."""
    p = str(tmp_path / "log.wal")
    fd = durable.open_append(p)
    try:
        durable.append_durable(fd, json.dumps({"n": 1}) + "\n", path=p)
        real_write = os.write
        monkeypatch.setattr(
            os, "write", lambda f, d: real_write(f, d[: len(d) // 2])
        )
        with pytest.raises(durable.ShortWriteError):
            durable.append_durable(fd, json.dumps({"n": 2}) + "\n", path=p)
        monkeypatch.undo()
    finally:
        os.close(fd)
    records, torn = durable.read_wal(p)
    assert records == [{"n": 1}]
    assert torn


def test_read_wal_torn_tail_vs_mid_file_garbage(tmp_path):
    torn_file = str(tmp_path / "torn.wal")
    with open(torn_file, "wb") as f:
        f.write(b'{"n": 1}\n{"n": 2}\n{"n": 3')  # crash tail
    records, torn = durable.read_wal(torn_file)
    assert records == [{"n": 1}, {"n": 2}]
    assert torn

    corrupt = str(tmp_path / "corrupt.wal")
    with open(corrupt, "wb") as f:
        f.write(b'{"n": 1}\ngarb@ge\n{"n": 3}\n')  # garbage MID-file
    with pytest.raises(durable.CorruptWalError):
        durable.read_wal(corrupt)


def test_read_json_tolerant_contract(tmp_path):
    p = str(tmp_path / "m.json")
    assert durable.read_json_tolerant(p, default={"d": 1}) == {"d": 1}
    with open(p, "wb") as f:
        f.write(b'{"step": 10')  # a tear only a non-compliant writer leaves
    assert durable.read_json_tolerant(p) is None
    durable.atomic_publish_json(p, {"step": 10})
    assert durable.read_json_tolerant(p) == {"step": 10}


# -- 2. crashsan semantics -------------------------------------------------


def test_record_enumerates_crossings(tmp_path):
    p = str(tmp_path / "reg.json")
    with crashsan.record() as ops:
        durable.atomic_publish_json(p, {"v": 1})
        durable.atomic_publish_json(p, {"v": 2})
        fd = durable.open_append(str(tmp_path / "log.wal"))
        try:
            durable.append_durable(fd, b"x\n", path=str(tmp_path / "log.wal"))
        finally:
            os.close(fd)
    assert [(o["index"], o["kind"]) for o in ops] == [
        (0, "publish"), (1, "publish"), (2, "append"),
    ]
    # the per-file op index is what a chaos plan's op= matches
    assert [o["file_op"] for o in ops] == [0, 1, 0]
    assert ops[0]["file"] == "reg.json"


def test_crash_at_counts_relative_crossings(tmp_path):
    p = str(tmp_path / "state.json")
    durable.atomic_publish_json(p, {"v": 1})  # before arming: not counted
    with pytest.raises(crashsan.CrashPoint):
        with crashsan.crash_at(1, "rename_lost"):
            durable.atomic_publish_json(p, {"v": 2})  # op 0: survives
            durable.atomic_publish_json(p, {"v": 3})  # op 1: dies
    assert durable.read_json_tolerant(p) == {"v": 2}


def test_arm_requires_sanitizer_enabled(tmp_path, monkeypatch):
    monkeypatch.setenv("GRAFT_CRASHSAN", "0")
    with pytest.raises(crashsan.CrashSanError):
        crashsan.arm(0, "tmp_torn")
    # disabled note_op is a no-op: nothing counted, nothing recorded
    with crashsan.record() as ops:
        durable.atomic_publish_json(str(tmp_path / "f.json"), {})
    assert ops == []
    assert crashsan.op_count() == 0


def test_arm_rejects_unknown_mode():
    with pytest.raises(crashsan.CrashSanError):
        crashsan.arm(0, "torn_sideways")


# -- 3. on-disk crash states per mode --------------------------------------


#: the staged bytes of the crashed publish, and the torn prefix (half)
#: crashsan's simulate leaves of them.
_V2 = json.dumps({"v": 2}).encode("utf-8")
_V2_TORN = _V2[: len(_V2) // 2]


def _publish_then_crash(tmp_path, mode):
    p = str(tmp_path / "state.json")
    durable.atomic_publish_json(p, {"v": 1})
    with pytest.raises(crashsan.CrashPoint):
        with crashsan.crash_at(0, mode):
            durable.atomic_publish_json(p, {"v": 2})
    return p


def test_publish_tmp_torn_leaves_previous_version(tmp_path):
    p = _publish_then_crash(tmp_path, "tmp_torn")
    assert durable.read_json_tolerant(p) == {"v": 1}
    torn_tmps = [f for f in os.listdir(tmp_path) if ".tmp" in f]
    assert len(torn_tmps) == 1  # the torn temp is on disk, never renamed
    with open(tmp_path / torn_tmps[0], "rb") as f:
        assert f.read() == _V2_TORN  # half of the staged bytes


def test_publish_rename_lost_leaves_previous_version(tmp_path):
    p = _publish_then_crash(tmp_path, "rename_lost")
    assert durable.read_json_tolerant(p) == {"v": 1}
    (tmp,) = [f for f in os.listdir(tmp_path) if ".tmp" in f]
    with open(tmp_path / tmp, "rb") as f:
        assert json.loads(f.read()) == {"v": 2}  # complete, never renamed


def test_publish_published_torn_reads_as_nothing(tmp_path):
    """The non-compliant-writer mode: the TARGET itself is torn.  A
    compliant atomic_publish can never produce this; the tolerant reader
    must still land in its fallback, not crash or half-parse."""
    p = _publish_then_crash(tmp_path, "published_torn")
    with open(p, "rb") as f:
        assert f.read() == _V2_TORN
    assert durable.read_json_tolerant(p, default="fallback") == "fallback"


def test_append_torn_append_is_a_tolerated_tail(tmp_path):
    p = str(tmp_path / "log.wal")
    fd = durable.open_append(p)
    try:
        durable.append_durable(fd, json.dumps({"n": 1}) + "\n", path=p)
        with pytest.raises(crashsan.CrashPoint):
            with crashsan.crash_at(0, "torn_append"):
                durable.append_durable(
                    fd, json.dumps({"n": 2}) + "\n", path=p
                )
    finally:
        os.close(fd)
    records, torn = durable.read_wal(p)
    assert records == [{"n": 1}]
    assert torn


def test_append_lost_leaves_exact_prefix(tmp_path):
    p = str(tmp_path / "log.wal")
    fd = durable.open_append(p)
    try:
        durable.append_durable(fd, json.dumps({"n": 1}) + "\n", path=p)
        with pytest.raises(crashsan.CrashPoint):
            with crashsan.crash_at(0, "append_lost"):
                durable.append_durable(
                    fd, json.dumps({"n": 2}) + "\n", path=p
                )
    finally:
        os.close(fd)
    records, torn = durable.read_wal(p)
    assert records == [{"n": 1}]
    assert not torn  # the bytes died in the page cache: no tear at all


def test_replace_modes(tmp_path):
    p = str(tmp_path / "cache.bin")
    durable.atomic_publish(p, b"version-one!")
    for mode, expect in (
        ("tmp_torn", b"version-one!"),    # temp torn, target untouched
        ("rename_lost", b"version-one!"),  # temp complete, never renamed
    ):
        tmp = durable.tmp_path(p)
        with open(tmp, "wb") as f:
            f.write(b"version-two!")
        with pytest.raises(crashsan.CrashPoint):
            with crashsan.crash_at(0, mode):
                durable.atomic_replace(tmp, p)
        with open(p, "rb") as f:
            assert f.read() == expect, mode
        if os.path.exists(tmp):
            os.unlink(tmp)


# -- 4. the matrix ---------------------------------------------------------


def test_matrix_every_crash_point_recovers():
    from tools.crashsan_matrix import run_matrix

    out = run_matrix()
    s = out["summary"]
    assert s["unrecovered"] == 0, [
        r for r in out["rows"] if not r["recovered"]
    ]
    assert s["recovered"] == s["injected"]
    # 7 journal ops + 3 registry publishes + 2 manifest publishes
    assert s["crash_points"] == 12
    assert s["injected"] == sum(s["by_scenario"].values())
    # every contract class is exercised at least once
    assert set(s["by_contract"]) == {
        "exact-prefix", "fallback-empty", "previous-version",
        "watermark-fallback",
    }


@pytest.mark.parametrize("mode", ["rename_lost", "tmp_torn"])
def test_journal_membership_survives_rotation_crash(tmp_path, mode):
    """The r18 regression, as a named crash point: a crash DURING rotation
    (op 4) must leave the membership record (op 3) readable — under the
    old two-step rotation it could land in NEITHER the new base nor the
    old WAL."""
    from tools.crashsan_matrix import journal_expected, run_journal

    records, torn = run_journal(str(tmp_path), crash=(4, mode))
    assert records == journal_expected(4)
    assert {"kind": "membership", "version": 7} in records
    assert not torn


# -- 5. the chaos grammar end ----------------------------------------------


def test_chaos_torn_write_parse():
    (f,) = chaos.parse_plan(
        "torn_write:file=master_journal.wal,op=3,mode=rename_lost"
    )
    assert f.kind == "torn_write"
    assert f.file == "master_journal.wal"
    assert f.op == 3
    assert f.mode == "rename_lost"

    with pytest.raises(chaos.ChaosError):  # typo'd mode fails at parse
        chaos.parse_plan("torn_write:file=x.wal,mode=torn_sideways")
    with pytest.raises(chaos.ChaosError):  # basename only, never a path
        chaos.parse_plan("torn_write:file=/var/run/x.wal,op=0")
    with pytest.raises(chaos.ChaosError):  # a crash point is one op
        chaos.parse_plan("torn_write:file=x.wal,op=-1")
    with pytest.raises(chaos.ChaosError):  # rank= could never match
        chaos.parse_plan("torn_write:file=x.wal,rank=0")


def test_chaos_torn_write_fires_through_real_publish(tmp_path, monkeypatch):
    """End-to-end: a chaos plan addressing 'the 2nd durable op on
    pod_registry.json' produces the rename_lost state through a REAL
    atomic_publish and dies with the chaos kill code."""
    fired = []
    monkeypatch.setattr(crashsan, "_exit", lambda code: fired.append(code))
    chaos.configure(
        "torn_write:file=pod_registry.json,op=1,mode=rename_lost"
    )
    p = str(tmp_path / "pod_registry.json")
    durable.atomic_publish_json(p, {"v": 1})  # op 0: no match
    # _exit is stubbed to return, so the simulated death falls through to
    # CrashPoint — letting one test observe both the exit code and halt.
    with pytest.raises(crashsan.CrashPoint):
        durable.atomic_publish_json(p, {"v": 2})  # op 1: dies
    assert fired == [chaos.CHAOS_KILL_EXIT_CODE]
    assert durable.read_json_tolerant(p) == {"v": 1}
