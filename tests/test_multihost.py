"""Executable multi-host training (SURVEY.md §3.5; VERDICT r2 Missing #2).

Three layers of evidence:

1. Unit: the master's GetGroupTask lockstep log — every process of a world
   walks the identical task sequence; version changes invalidate the log and
   requeue the group's in-flight tasks.
2. In-process: two Worker loops in group mode (threads, shared servicer)
   execute the same tasks and exactly one reports.
3. Integration: TWO real worker processes join one ``jax.distributed`` world
   over localhost (4 fake CPU devices each, 8-device global mesh), train
   lockstep through the gRPC master, one is SIGKILLed, the survivor restarts
   via RESTART_EXIT_CODE and the relaunched single-host worker resumes from
   the pre-restart snapshot and finishes the job.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.data.synthetic import generate
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher


def _shards(tmp_path, n_records=64, records_per_task=16, name="train.rio"):
    path = str(tmp_path / name)
    generate("mnist", path, n_records)
    reader = create_data_reader(path)
    return path, reader, reader.create_shards(records_per_task)


# ---------------------------------------------------------------------------
# 1. GetGroupTask semantics
# ---------------------------------------------------------------------------


def test_group_task_lockstep_same_sequence(tmp_path):
    """Two processes pulling the same seqs get the same tasks, regardless of
    interleaving; the log survives out-of-order arrival."""
    _, _, shards = _shards(tmp_path)
    servicer = MasterServicer(TaskDispatcher(shards))
    servicer.RegisterWorker({"worker_id": "w-a"})
    v = servicer.RegisterWorker({"worker_id": "w-b"})["version"]

    # Until EVERY member confirms the current version, no collective task is
    # issued (a stale member would wedge its peers inside the collective).
    r = servicer.GetGroupTask({"worker_id": "w-a", "seq": 0, "version": v})
    assert r == {"task": None, "finished": False, "stale": False}
    servicer.Heartbeat({"worker_id": "w-a", "version": v})

    seq_a, seq_b = [], []
    # a pulls ahead two entries, then b catches up, then interleave.
    for seq, out in ((0, seq_a), (1, seq_a), (0, seq_b), (1, seq_b),
                     (2, seq_b), (2, seq_a), (3, seq_a), (3, seq_b)):
        r = servicer.GetGroupTask({"worker_id": "w", "seq": seq, "version": v})
        assert not r["stale"]
        out.append((r["task"] or {}).get("task_id"))
    assert seq_a == seq_b
    assert len({t for t in seq_a if t is not None}) == 4  # distinct tasks

    # report them (rank 0's job); later seqs drain the queue and mark finished
    for tid in seq_a:
        servicer.ReportTaskResult(
            {"worker_id": "w-a", "task_id": tid, "success": True,
             "task_type": "training"}
        )
    r = servicer.GetGroupTask({"worker_id": "w", "seq": 4, "version": v})
    assert r["task"] is None and r["finished"] and not r["stale"]
    # the finished marker is logged: the peer sees the identical terminal entry
    r2 = servicer.GetGroupTask({"worker_id": "w", "seq": 4, "version": v})
    assert r2 == r


def test_group_task_stale_on_version_change_and_requeue(tmp_path):
    """A membership bump invalidates the old world's log; its in-flight tasks
    requeue as soon as the new world asks for work."""
    _, _, shards = _shards(tmp_path)
    dispatcher = TaskDispatcher(shards)
    servicer = MasterServicer(dispatcher)
    v1 = servicer.RegisterWorker({"worker_id": "w-a"})["version"]
    r = servicer.GetGroupTask({"worker_id": "w-a", "seq": 0, "version": v1})
    assert r["task"] is not None
    assert dispatcher.counts()["doing"] == 1

    v2 = servicer.RegisterWorker({"worker_id": "w-b"})["version"]
    assert v2 != v1
    # old world is told it is stale
    stale = servicer.GetGroupTask({"worker_id": "w-a", "seq": 1, "version": v1})
    assert stale["stale"]
    servicer.Heartbeat({"worker_id": "w-a", "version": v2})  # w-a re-confirms
    # new world's first pull resets the log and requeues the orphaned task
    r2 = servicer.GetGroupTask({"worker_id": "w-b", "seq": 0, "version": v2})
    assert not r2["stale"] and r2["task"] is not None
    assert r2["task"]["task_id"] == r["task"]["task_id"]  # requeued, re-issued


def test_group_task_seq_ahead_is_stale(tmp_path):
    _, _, shards = _shards(tmp_path)
    servicer = MasterServicer(TaskDispatcher(shards))
    v = servicer.RegisterWorker({"worker_id": "w-a"})["version"]
    assert servicer.GetGroupTask(
        {"worker_id": "w-a", "seq": 7, "version": v}
    )["stale"]


# ---------------------------------------------------------------------------
# 2. Two in-process workers in lockstep group mode
# ---------------------------------------------------------------------------


def test_two_workers_lockstep_in_process(tmp_path, devices):
    """Both group-mode workers execute every task (their steps would be one
    collective on a real multi-host mesh); only rank 0 reports."""
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker

    path, reader, shards = _shards(tmp_path)
    dispatcher = TaskDispatcher(shards)
    servicer = MasterServicer(dispatcher)
    config = JobConfig(
        model_def="mnist.model_spec",
        training_data=path,
        minibatch_size=16,
        multihost=True,
    )
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )

    # Register BOTH up front (as worker.main does) so neither sees a
    # membership bump mid-run (multihost bumps raise WorkerRestartRequired).
    memberships = {
        w: servicer.RegisterWorker({"worker_id": w}) for w in ("w-a", "w-b")
    }
    memberships["w-a"] = memberships["w-b"]  # both hold the final view

    workers = {
        w: Worker(
            config, DirectMasterProxy(servicer), reader,
            worker_id=w, spec=spec, devices=devices,
        )
        for w in ("w-a", "w-b")
    }
    results, errors = {}, {}

    def run(w):
        try:
            results[w] = workers[w].run(membership=memberships[w])
        except Exception as e:  # pragma: no cover - surfaced by asserts
            errors[w] = e

    threads = [threading.Thread(target=run, args=(w,)) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert results["w-a"]["tasks_done"] == results["w-b"]["tasks_done"] == 4
    # every task ran on both workers, but the master saw each exactly once
    assert servicer.dispatcher.counts()["done"] == 4
    assert servicer.dispatcher.finished()


def test_heartbeat_revival_does_not_confirm(tmp_path):
    """An evicted worker revived by a bare heartbeat must NOT count as
    having confirmed the topology (its address is gone and it never applied
    the post-revival membership) — otherwise the lockstep log would issue
    collective work to a split-brain world."""
    t = [0.0]
    rdv = RendezvousServer(heartbeat_timeout_s=5.0, clock=lambda: t[0])
    rdv.register("w-a", address="10.0.0.1")
    t[0] = 10.0
    assert rdv.reap_dead() == ["w-a"]
    v = rdv.heartbeat("w-a")  # background-thread beat: no version
    assert "w-a" in rdv.membership()["workers"]
    assert not rdv.all_confirmed(v)
    # a version-carrying heartbeat (the worker re-applied) confirms
    rdv.heartbeat("w-a", version=v)
    assert rdv.all_confirmed(v)


def test_group_task_failure_forces_resync(tmp_path, devices):
    """A lockstep member that fails a task must requeue it, actively leave
    the membership (so peers resync instead of wedging in a collective), and
    restart — NOT swallow the error and run ahead of the group."""
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import (
        DirectMasterProxy,
        Worker,
        WorkerRestartRequired,
    )

    path, reader, shards = _shards(tmp_path)
    dispatcher = TaskDispatcher(shards)
    servicer = MasterServicer(dispatcher)
    config = JobConfig(
        model_def="mnist.model_spec",
        training_data=path,
        minibatch_size=16,
        multihost=True,
    )
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )

    class FailingReader:
        def read_records(self, shard):
            raise IOError("storage hiccup")

    servicer.RegisterWorker({"worker_id": "w-a"})
    membership = servicer.RegisterWorker({"worker_id": "w-b"})
    servicer.Heartbeat({"worker_id": "w-a", "version": membership["version"]})
    worker = Worker(
        config, DirectMasterProxy(servicer), FailingReader(),
        worker_id="w-b", spec=spec, devices=devices,
    )
    with pytest.raises(WorkerRestartRequired, match="lockstep"):
        worker.run(membership=membership)
    m = servicer.GetMembership({})
    assert "w-b" not in m["workers"]  # actively left -> peers resync
    counts = dispatcher.counts()
    assert counts["doing"] == 0 and counts["todo"] == 4  # task requeued


# ---------------------------------------------------------------------------
# 3. Real 2-process jax.distributed world over localhost
# ---------------------------------------------------------------------------


def _free_port() -> int:
    # common.platform is jax-free: the shared helper without the jax
    # import parallel.distributed would drag in.
    from elasticdl_tpu.common.platform import free_port

    return free_port()


_incarnation = {}  # (log_dir, worker_id) -> launch count (per-test isolation)


def _spawn_worker(worker_id: str, config: JobConfig, log_dir) -> subprocess.Popen:
    env = dict(os.environ)
    env.update(config.to_env())
    env["ELASTICDL_WORKER_ID"] = worker_id
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    # Per-test compile cache, shared by the gang: incarnations re-join
    # without recompiling, and — critically — the cache state stays
    # SYMMETRIC across gang members.  A global cache left one member with a
    # warm hit and the other compiling cold, and that skew (under 1-core
    # contention) outlived XLA:CPU's hard 30 s Gloo context-init window,
    # collapsing every world formation.
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(str(log_dir), "jax_cache")
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never grab the real TPU tunnel
    # One log file PER INCARNATION: tail checks (fatal-marker classification)
    # must see only the CURRENT incarnation — a stale marker from a previous
    # life would misclassify a fresh crash as a relaunchable fatal — while
    # whole-run assertions read every incarnation's file.
    key = (str(log_dir), worker_id)
    n = _incarnation.get(key, 0)
    _incarnation[key] = n + 1
    log = open(os.path.join(log_dir, f"{worker_id}.log.{n}"), "w")
    return subprocess.Popen(
        [sys.executable, "-m", "elasticdl_tpu.worker.main"],
        env=env, stdout=log, stderr=subprocess.STDOUT, cwd="/root/repo",
    )


def _latest_log(log_dir, worker_id: str) -> str:
    """The CURRENT incarnation's full output."""
    n = _incarnation.get((str(log_dir), worker_id), 1) - 1
    path = os.path.join(log_dir, f"{worker_id}.log.{n}")
    return open(path).read() if os.path.exists(path) else ""


def _all_logs(log_dir, worker_id: str) -> str:
    """Every incarnation's output, concatenated launch order."""
    out = []
    for n in range(_incarnation.get((str(log_dir), worker_id), 0)):
        path = os.path.join(log_dir, f"{worker_id}.log.{n}")
        if os.path.exists(path):
            out.append(open(path).read())
    return "".join(out)


@pytest.mark.slow
def test_real_process_scale_4_8_4(tmp_path):
    """The BASELINE config-#5 scale story with REAL processes (the older
    in-process test emulates membership over a fixed pool): one worker
    process (4 fake devices) trains alone, a second joins (the world re-forms
    to 8 devices via RESTART + jax.distributed re-init), then the joiner is
    killed and the survivor drains the job back at 4 devices."""
    from elasticdl_tpu.worker.worker import RESTART_EXIT_CODE

    path, _, shards = _shards(
        tmp_path, n_records=256, records_per_task=32, name="train.rio"
    )
    # Long task stream: the joiner needs ~15s to boot (jax import +
    # distributed init), and the solo phase must not drain the job first.
    dispatcher = TaskDispatcher(shards, num_epochs=60)
    # 20 s reaper: a joiner compiling under 1-core contention (the incumbent
    # saturates the core since the r4 fused-scan loop) can starve its
    # liveness thread past 6 s; evicting it mid-join collapses the world.
    rendezvous = RendezvousServer(heartbeat_timeout_s=20.0)
    servicer = MasterServicer(dispatcher, rendezvous=rendezvous)
    from elasticdl_tpu.master.servicer import MasterServer

    server = MasterServer(servicer, port=0).start()
    stop = threading.Event()

    def reap():
        while not stop.is_set():
            rendezvous.reap_dead()
            time.sleep(0.25)

    threading.Thread(target=reap, daemon=True).start()

    config = JobConfig(
        model_def="mnist.model_spec",
        model_params="compute_dtype=float32",
        training_data=path,
        minibatch_size=16,
        master_addr=server.address,
        multihost=True,
        coordinator_port=_free_port(),
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_steps=4,
        num_epochs=60,
        # This harness runs 3 python processes on ONE core: a freshly joined
        # peer's coordination heartbeats can starve >30 s during restore +
        # first compile, and the r4 default (30 s) then produces FALSE
        # peer-death that churns the world until the phase deadline.  Use
        # the conservative bound this scenario needs (JAX's own default,
        # what r3 implicitly ran with); kill-driven tests keep the fast
        # default so aborts stay quick.
        distributed_heartbeat_timeout_s=100.0,
        # The r4 fused-scan loop saturates the core; a solo incumbent then
        # starves the JOINER's cold compile past XLA:CPU's hard 30 s Gloo
        # context-init window, collapsing every world formation on this
        # 1-core harness.  The per-batch path leaves the scheduler slack
        # the join needs; the fused path's multi-process correctness is
        # covered by test_two_process_distributed_train_kill_resume, where
        # the gang compiles symmetrically.  (r5: said directly via the
        # dedicated flag — prefetch_depth=0 no longer implies it.)
        prefetch_depth=0,
        fused_task_scan=False,
        task_pipelining=False,
    )
    procs: dict = {}

    def _log_tail(w):
        return _latest_log(tmp_path, w)[-3000:]

    def supervise_until(cond, deadline_s):
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            if cond():
                return
            for w, p in list(procs.items()):
                rc = p.poll()
                if rc is None:
                    continue
                fatal = (
                    "JAX distributed service detected fatal errors"
                    in _log_tail(w)
                )
                if rc == RESTART_EXIT_CODE or fatal:
                    procs[w] = _spawn_worker(w, config, tmp_path)
                else:
                    pytest.fail(f"{w} exited rc={rc}; log:\n" + _log_tail(w))
            time.sleep(0.5)
        pytest.fail("condition not reached; logs:\n"
                    + "".join(_log_tail(w) for w in procs))

    try:
        # Phase 1: one worker, world of 1 (4 devices).
        procs["w-a"] = _spawn_worker("w-a", config, tmp_path)
        supervise_until(
            lambda: servicer.JobStatus({})["done"] >= 2
            and rendezvous.membership()["world_size"] == 1,
            deadline_s=120,
        )

        # Phase 2: scale up — second process joins; both must re-form into
        # one 2-process world (8 devices) and make lockstep progress.
        done_at_join = servicer.JobStatus({})["done"]
        procs["w-b"] = _spawn_worker("w-b", config, tmp_path)
        supervise_until(
            lambda: rendezvous.membership()["world_size"] == 2
            and servicer.JobStatus({})["done"] >= done_at_join + 2
            and servicer._group_version is not None,  # lockstep log active
            deadline_s=240,
        )

        # Phase 3: scale down — kill the joiner; the survivor restarts into
        # a world of 1 and the job drains to completion.
        procs.pop("w-b").send_signal(signal.SIGKILL)
        supervise_until(
            lambda: servicer.JobStatus({})["finished"], deadline_s=300
        )
        # the dead joiner was reaped out of the membership
        assert "w-b" not in rendezvous.membership()["workers"]
    finally:
        stop.set()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        server.stop()


@pytest.mark.slow
def test_two_process_distributed_train_kill_resume(tmp_path):
    """The 2-process proof (VERDICT r2 next-round task 3): a real
    jax.distributed world of two worker PROCESSES (8-device global mesh)
    trains through the gRPC master in lockstep; killing one process evicts it
    via the heartbeat reaper, the survivor exits RESTART_EXIT_CODE (after
    snapshotting), and its relaunch finishes the job single-host from the
    snapshot."""
    from elasticdl_tpu.common.rpc import JsonRpcClient
    from elasticdl_tpu.master.servicer import MasterServer
    from elasticdl_tpu.worker.worker import RESTART_EXIT_CODE

    path, _, shards = _shards(
        tmp_path, n_records=256, records_per_task=32, name="train.rio"
    )
    # Many epochs: a continuous task stream so the kill lands mid-training.
    dispatcher = TaskDispatcher(shards, num_epochs=6)
    rendezvous = RendezvousServer(heartbeat_timeout_s=6.0)
    servicer = MasterServicer(dispatcher, rendezvous=rendezvous)
    server = MasterServer(servicer, port=0).start()

    stop = threading.Event()

    def reap():
        while not stop.is_set():
            rendezvous.reap_dead()
            time.sleep(0.25)

    reaper = threading.Thread(target=reap, daemon=True)
    reaper.start()

    config = JobConfig(
        model_def="mnist.model_spec",
        model_params="compute_dtype=float32",
        training_data=path,
        minibatch_size=16,
        master_addr=server.address,
        multihost=True,
        coordinator_port=_free_port(),
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_steps=4,
        num_epochs=6,
    )

    procs: dict = {}
    relaunches = {"count": 0}

    def _log_tail(w):
        return _latest_log(tmp_path, w)[-3000:]

    def supervise_until(cond, deadline_s, max_relaunch=8):
        """Emulate the PodManager: relaunch membership-driven exits — rc=3
        (graceful RESTART) and jax.distributed runtime fatals (a peer's
        restart kills everyone attached to its coordinator).  Any other exit
        is a real failure."""
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            if cond():
                return
            for w, p in list(procs.items()):
                rc = p.poll()
                if rc is None:
                    continue
                runtime_fatal = (
                    "JAX distributed service detected fatal errors"
                    in _log_tail(w)
                )
                if rc == RESTART_EXIT_CODE or runtime_fatal:
                    assert relaunches["count"] < max_relaunch, (
                        f"{w} restart churn; log:\n" + _log_tail(w)
                    )
                    relaunches["count"] += 1
                    procs[w] = _spawn_worker(w, config, tmp_path)
                else:
                    pytest.fail(f"{w} exited rc={rc}; log:\n" + _log_tail(w))
            time.sleep(0.5)
        pytest.fail(
            "condition not reached; logs:\n"
            + "".join(_log_tail(w) for w in procs)
        )

    try:
        procs.update(
            {w: _spawn_worker(w, config, tmp_path) for w in ("w-a", "w-b")}
        )
        client = JsonRpcClient(server.address)
        client.wait_ready(30)

        # Phase 1: lockstep training demonstrably progresses with world=2.
        supervise_until(
            lambda: servicer.JobStatus({})["done"] >= 4
            and servicer.rendezvous.membership()["world_size"] == 2,
            deadline_s=240,
        )

        # Phase 2: kill one process.  The survivor must notice (heartbeat
        # version bump or a collective error), snapshot, and exit
        # RESTART_EXIT_CODE.
        procs.pop("w-b").send_signal(signal.SIGKILL)
        survivor = procs["w-a"]
        try:
            rc = survivor.wait(timeout=150)
        except subprocess.TimeoutExpired:  # pragma: no cover - belt & braces
            # Production's pod liveness probe would reap a fully wedged
            # survivor; the resume path below is identical either way.
            survivor.kill()
            survivor.wait(timeout=10)
            rc = None
        # Two legitimate terminations: (a) the kill landed between tasks —
        # the heartbeat reaper bumps the version and the survivor exits
        # RESTART_EXIT_CODE gracefully; (b) the kill landed mid-collective
        # (or mid checkpoint barrier) — the survivor wedges inside the op
        # until the jax.distributed coordination service declares the peer
        # unhealthy and fatally terminates the process ("Terminating
        # process because the JAX distributed service detected fatal
        # errors").  Both are "peer loss detected"; a clean exit or an
        # unhandled Python error without the fatal marker is a real failure.
        runtime_fatal = (
            "JAX distributed service detected fatal errors" in _log_tail("w-a")
        )
        assert rc in (RESTART_EXIT_CODE, None) or runtime_fatal, (
            f"survivor exited {rc}, log:\n" + _log_tail("w-a")
        )
        done_before = servicer.JobStatus({})["done"]
        # The periodic (collective) checkpoints were reported along the way;
        # the relaunch resumes from them.
        assert servicer.GetCheckpoint({})["path"], "no checkpoint reported"

        # Phase 3: the relaunched worker (now a world of 1, single-host mode)
        # resumes and drains the job.
        procs["w-a"] = _spawn_worker("w-a", config, tmp_path)
        supervise_until(
            lambda: servicer.JobStatus({})["finished"], deadline_s=300
        )
        rc2 = procs["w-a"].wait(timeout=60)
        assert rc2 == 0, f"relaunched worker rc={rc2}; log:\n" + _log_tail("w-a")
        assert servicer.JobStatus({})["done"] > done_before
    finally:
        stop.set()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        server.stop()


def _supervise(procs, spawn, cond, deadline_s, log_tail,
               max_relaunch=8):
    """Shared supervision loop: emulate the PodManager by relaunching
    membership-driven exits (RESTART_EXIT_CODE / jax.distributed runtime
    fatals), treating rc=0 as a clean retirement and anything else as a
    test failure.  Returns when ``cond()`` holds."""
    from elasticdl_tpu.worker.worker import RESTART_EXIT_CODE

    relaunches = 0
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if cond():
            return
        for w, p in list(procs.items()):
            rc = p.poll()
            if rc is None:
                continue
            if rc == 0:
                procs.pop(w)
                continue
            fatal = (
                "JAX distributed service detected fatal errors"
                in log_tail(w)
            )
            if rc == RESTART_EXIT_CODE or fatal:
                assert relaunches < max_relaunch, (
                    f"{w} restart churn; log:\n" + log_tail(w)
                )
                relaunches += 1
                procs[w] = spawn(w)
            else:
                pytest.fail(f"{w} exited rc={rc}; log:\n" + log_tail(w))
        time.sleep(0.5)
    pytest.fail("condition not reached; logs:\n"
                + "".join(log_tail(w) for w in list(procs)))


@pytest.mark.slow
def test_two_process_hierarchical_mesh_trains(tmp_path):
    """The hierarchical mesh's flagship layout, proven with REAL processes:
    dcn_data_parallelism=2 over a 2-process jax.distributed world puts the
    dp axis exactly on the PROCESS boundary (each process contributes one
    4-device ep slice) — gradient psums cross processes, collectives inside
    a step stay within each process's devices.  Lockstep progress must
    happen AT world=2 (a long task stream keeps a faster-booting worker from
    draining the job solo), and no worker may have fallen back to a flat
    mesh."""
    path, _, shards = _shards(
        tmp_path, n_records=256, records_per_task=32, name="train.rio"
    )
    dispatcher = TaskDispatcher(shards, num_epochs=60)  # continuous stream
    rendezvous = RendezvousServer(heartbeat_timeout_s=6.0)
    servicer = MasterServicer(dispatcher, rendezvous=rendezvous)
    from elasticdl_tpu.master.servicer import MasterServer

    server = MasterServer(servicer, port=0).start()
    stop = threading.Event()

    def reap():
        while not stop.is_set():
            rendezvous.reap_dead()
            time.sleep(0.25)

    threading.Thread(target=reap, daemon=True).start()

    config = JobConfig(
        model_def="mnist.model_spec",
        model_params="compute_dtype=float32",
        training_data=path,
        minibatch_size=16,
        master_addr=server.address,
        multihost=True,
        coordinator_port=_free_port(),
        num_epochs=60,
        dcn_data_parallelism=2,
    )
    procs = {}

    def _log_tail(w):
        return _latest_log(tmp_path, w)[-3000:]

    def _full_log(w):
        return _all_logs(tmp_path, w)

    try:
        procs.update(
            {w: _spawn_worker(w, config, tmp_path) for w in ("w-a", "w-b")}
        )
        # The PROOF condition: tasks complete while the world is 2 and the
        # lockstep log is live — progress made BY the hierarchical layout.
        done_floor = {"at2": None}

        def lockstep_progress():
            if rendezvous.membership()["world_size"] != 2:
                return False
            done = servicer.JobStatus({})["done"]
            if done_floor["at2"] is None:
                done_floor["at2"] = done
                return False
            return done >= done_floor["at2"] + 4

        _supervise(
            procs, lambda w: _spawn_worker(w, config, tmp_path),
            lockstep_progress, deadline_s=300, log_tail=_log_tail,
        )
        # The hierarchical mesh really ran: search the WHOLE log of BOTH
        # workers, every incarnation (append-mode logs; a retired rc=0
        # worker must be checked too).
        for w in ("w-a", "w-b"):
            assert "falling back to a flat 1-D mesh" not in _full_log(w)
    finally:
        stop.set()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        server.stop()


# ---------------------------------------------------------------------------
# 4. r6 gang-mode hot-path parity: prep-ahead pipelining + non-blocking
#    group checkpoints
# ---------------------------------------------------------------------------


def _lockstep_pair(tmp_path, devices, reader, servicer, **cfg_kwargs):
    """Two in-process group-mode workers over one servicer, both registered
    up front (the test_two_workers_lockstep_in_process harness)."""
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker

    config = JobConfig(
        model_def="mnist.model_spec",
        minibatch_size=16,
        multihost=True,
        **cfg_kwargs,
    )
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )
    memberships = {
        w: servicer.RegisterWorker({"worker_id": w}) for w in ("w-a", "w-b")
    }
    memberships["w-a"] = memberships["w-b"]  # both hold the final view
    workers = {
        w: Worker(
            config, DirectMasterProxy(servicer), reader,
            worker_id=w, spec=spec, devices=devices,
        )
        for w in ("w-a", "w-b")
    }
    return workers, memberships


def _run_pair(workers, memberships):
    results, errors = {}, {}

    def run(w):
        try:
            results[w] = workers[w].run(membership=memberships[w])
        except Exception as e:  # pragma: no cover - surfaced by asserts
            errors[w] = e

    threads = [threading.Thread(target=run, args=(w,)) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    return results


def test_group_prep_ahead_pipelined_lockstep(tmp_path, devices):
    """r6 tentpole: with the ``not _group_mode`` gate lifted, lockstep
    workers run the prep-ahead pipeline — every task's host decode/stack
    happens on the background prep thread, while its DISPATCH stays inside
    the lockstep boundary (both members dispatch the identical task order,
    every dispatch carrying a prepped payload)."""
    path, reader, shards = _shards(tmp_path)
    servicer = MasterServicer(TaskDispatcher(shards))
    workers, memberships = _lockstep_pair(
        tmp_path, devices, reader, servicer,
        training_data=path, fused_task_scan=True, task_pipelining=True,
    )

    prep_threads = {w: [] for w in workers}
    dispatch_order = {w: [] for w in workers}
    for w, worker in workers.items():
        orig_prep = worker._prep_fused_host
        orig_dispatch = worker._dispatch_training_task

        def spy_prep(task, _w=w, _orig=orig_prep):
            prep_threads[_w].append(threading.current_thread().name)
            return _orig(task)

        def spy_dispatch(task, prep=None, _w=w, _orig=orig_dispatch):
            dispatch_order[_w].append((task.task_id, prep is not None))
            return _orig(task, prep=prep)

        worker._prep_fused_host = spy_prep
        worker._dispatch_training_task = spy_dispatch

    results = _run_pair(workers, memberships)
    assert results["w-a"]["tasks_done"] == results["w-b"]["tasks_done"] == 4
    assert servicer.dispatcher.counts()["done"] == 4  # exactly one report
    assert servicer.dispatcher.finished()
    for w, worker in workers.items():
        assert worker._group_mode, w
        # the gate is gone: pipelining reports enabled in group mode
        assert worker._pipelining_enabled(), w
        # prep ran, and ran on the background prep thread
        assert len(prep_threads[w]) == 4, (w, prep_threads)
        assert all(n.startswith("edl-prep") for n in prep_threads[w]), (
            w, prep_threads,
        )
        # every dispatch consumed a prepped payload
        assert all(had_prep for _, had_prep in dispatch_order[w]), (
            w, dispatch_order,
        )
    # lockstep boundary: both members dispatched the identical task order
    assert dispatch_order["w-a"] == dispatch_order["w-b"]
    # EVERY rank's phase snapshot reaches the master: rank 0's rides its
    # reports, the other rank's rides the heartbeat (reports are
    # rank-0-gated) — a straggler rank must be visible per-worker
    status = servicer.JobStatus({})
    assert set(status["phase_times"]) == {"w-a", "w-b"}
    for w in ("w-a", "w-b"):
        assert status["phase_times"][w].get("dispatch", 0) > 0.0, w


def test_group_prep_drained_on_preemption(tmp_path, devices):
    """A group worker parking for preemption must hand its undispatched
    prepped task back to the master (failure report -> requeue), not hold
    it across the restart — and it must acknowledge the park BEFORE paying
    the abandon RPC (a slow master must not consume the snapshot window)."""
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import (
        DirectMasterProxy,
        Worker,
        WorkerRestartRequired,
    )

    path, reader, shards = _shards(tmp_path)
    dispatcher = TaskDispatcher(shards)
    servicer = MasterServicer(dispatcher)
    config = JobConfig(
        model_def="mnist.model_spec", training_data=path, minibatch_size=16,
        multihost=True, fused_task_scan=True, task_pipelining=True,
    )
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )
    # Gang of two, but only w-b's loop runs — w-a is a confirmed phantom
    # peer (the lockstep log issues tasks once every member confirmed), so
    # the test observes the abandon without paying a full-job drain.
    servicer.RegisterWorker({"worker_id": "w-a"})
    membership = servicer.RegisterWorker({"worker_id": "w-b"})
    servicer.Heartbeat({"worker_id": "w-a", "version": membership["version"]})
    target = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w-b", spec=spec, devices=devices,
    )
    seen = {"parked_at_abandon": None, "abandoned_task": None}
    orig_call = target.master.call

    def spy_call(method, payload=None, **kw):
        if (
            method == "ReportTaskResult"
            and payload is not None
            and not payload.get("success", True)
            and seen["abandoned_task"] is None
        ):
            seen["parked_at_abandon"] = target._parked
            seen["abandoned_task"] = payload["task_id"]
        resp = orig_call(method, payload, **kw)
        # Preempt as soon as a prepped-but-undispatched task exists: the
        # NEXT loop iteration must park and abandon it.
        if target._prep_queue and not target._preempting:
            target._preempting = True
        return resp

    target.master.call = spy_call
    errors = {}

    def run_target():
        try:
            target.run(membership=membership)
        except Exception as e:
            errors["w-b"] = e

    t_b = threading.Thread(target=run_target)
    t_b.start()
    deadline = time.time() + 90
    while time.time() < deadline and seen["abandoned_task"] is None:
        time.sleep(0.05)
    assert seen["abandoned_task"] is not None, "prep never abandoned"
    # the park was acknowledged BEFORE the (potentially slow) abandon RPC
    assert seen["parked_at_abandon"] is True
    # the abandoned task went straight back to the todo queue
    assert dispatcher.counts()["todo"] >= 1
    # end the run without draining the job: un-park, then bump the
    # membership — the next membership check restarts the worker
    servicer.RegisterWorker({"worker_id": "w-c"})
    target._preempting = False
    t_b.join(timeout=60)
    assert isinstance(errors.get("w-b"), WorkerRestartRequired), errors


def test_group_checkpoint_nonblocking(tmp_path, devices):
    """r6 tentpole: the group-mode periodic checkpoint pays only the
    device-side snapshot at the lockstep boundary — the shard write runs on
    the background checkpoint thread on EVERY rank, completes durably, and
    the job-end final save settles any in-flight background save first."""
    path, reader, shards = _shards(tmp_path, n_records=128)
    servicer = MasterServicer(TaskDispatcher(shards))
    # Per-worker checkpoint dirs: the in-process harness emulates two
    # processes, and two CheckpointManagers racing one directory would test
    # the filesystem, not the worker.
    workers, memberships = _lockstep_pair(
        tmp_path, devices, reader, servicer,
        training_data=path, checkpoint_steps=2,
    )
    from elasticdl_tpu.common.checkpoint import CheckpointManager

    save_threads = {w: [] for w in workers}
    for w, worker in workers.items():
        worker._ckpt = CheckpointManager(str(tmp_path / f"ckpt_{w}"))
        orig_save = worker._ckpt.save

        def spy_save(step, state, wait=False, _w=w, _orig=orig_save):
            save_threads[_w].append(
                (threading.current_thread().name, int(step))
            )
            return _orig(step, state, wait=wait)

        worker._ckpt.save = spy_save

    results = _run_pair(workers, memberships)
    assert results["w-a"]["tasks_done"] == results["w-b"]["tasks_done"] == 8
    # the boundary cost and the background write are split in the phase
    # decomposition: checkpoint (snapshot + joins) on the critical path,
    # checkpoint_bg (write + commit) off it
    for w in workers:
        assert results[w]["phase_times"].get("checkpoint", 0) > 0.0, w
        assert results[w]["phase_times"].get("checkpoint_bg", 0) > 0.0, w
    for w, worker in workers.items():
        names = [n for n, _ in save_threads[w]]
        assert names, (w, save_threads)
        # every periodic save ran OFF the task loop, on the background
        # checkpoint thread — every rank participates (collective saves)
        assert any(n.startswith("edl-ckpt") for n in names), (w, names)
        # the job-end final save runs ON the worker thread, after joining
        # the in-flight background save
        assert not names[-1].startswith("edl-ckpt"), (w, names)
        # background saves completed durably
        steps_on_disk = worker._ckpt.all_steps()
        assert len(steps_on_disk) >= 2, (w, steps_on_disk)
        worker._ckpt.close()


def test_group_inflight_save_settles_before_preemption_exit(tmp_path, devices):
    """A group worker's preemption path never solo-saves, but it must JOIN
    an in-flight background collective save before the process exit can
    tear it (bounded by the grace window)."""
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker

    path, reader, shards = _shards(tmp_path)
    servicer = MasterServicer(TaskDispatcher(shards))
    config = JobConfig(
        model_def="mnist.model_spec", training_data=path, minibatch_size=16,
    )
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w-a", spec=spec, devices=devices,
    )
    worker._group_mode = True  # the preemption path's group branch
    done = {"t": None}

    def slow_save():
        time.sleep(0.5)
        done["t"] = time.monotonic()

    t = threading.Thread(target=slow_save, name="edl-ckpt")
    worker._ckpt_thread = t
    t.start()
    assert worker.preemption_snapshot() is False  # group mode never solo-saves
    t_return = time.monotonic()
    assert done["t"] is not None, "preemption exit did not join the save"
    assert t_return >= done["t"]
