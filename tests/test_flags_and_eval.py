"""Config-flag behavior (VERDICT r2 Missing #4) and eval-tail exactness
(VERDICT r2 Weak #4): every JobConfig field is honored — --max_steps drains
the job, --evaluation_steps=0 evals at each epoch boundary, --log_level
applies — and a wrap-padded eval tail yields EXACTLY the unsharded metric.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.data.reader import Shard, create_data_reader
from elasticdl_tpu.data.synthetic import generate
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import (
    TASK_EVALUATION,
    TASK_TRAINING,
    TaskDispatcher,
)


def _mk_shards(tmp_path, n=128, per_task=16, name="train.rio"):
    path = str(tmp_path / name)
    generate("mnist", path, n)
    reader = create_data_reader(path)
    return path, reader, reader.create_shards(per_task)


def test_max_steps_drains_job(tmp_path):
    """Once the reported model version reaches --max_steps, no further
    training tasks are handed out; in-flight work reports normally and the
    job finishes."""
    _, _, shards = _mk_shards(tmp_path)  # 8 tasks
    dispatcher = TaskDispatcher(shards, num_epochs=100)
    servicer = MasterServicer(dispatcher, max_steps=3)

    t1 = servicer.GetTask({"worker_id": "w0"})["task"]
    servicer.ReportTaskResult(
        {"worker_id": "w0", "task_id": t1["task_id"], "success": True,
         "task_type": TASK_TRAINING, "model_version": 2}
    )
    assert servicer.GetTask({"worker_id": "w0"})["task"] is not None  # < max

    t2 = servicer.GetTask({"worker_id": "w0"})["task"]
    servicer.ReportTaskResult(
        {"worker_id": "w0", "task_id": t2["task_id"], "success": True,
         "task_type": TASK_TRAINING, "model_version": 3}
    )
    # version hit max_steps -> queue drained; one in-flight task remains
    resp = servicer.GetTask({"worker_id": "w0"})
    assert resp["task"] is None
    # after the in-flight task reports, the job is finished
    for d in list(dispatcher._doing.values()):
        servicer.ReportTaskResult(
            {"worker_id": "w0", "task_id": d.task.task_id, "success": True,
             "task_type": TASK_TRAINING, "model_version": 4}
        )
    assert servicer.GetTask({"worker_id": "w0"})["finished"]


def test_epoch_end_eval_rounds(tmp_path):
    """--evaluation_steps=0: one eval round per epoch boundary, the final
    epoch's round doubling as the end-of-job eval."""
    _, _, shards = _mk_shards(tmp_path, n=32, per_task=16)  # 2 tasks/epoch
    _, _, eval_shards = _mk_shards(tmp_path, n=16, per_task=16, name="val.rio")
    dispatcher = TaskDispatcher(shards, num_epochs=3)
    evaluation = EvaluationService(eval_shards, evaluation_steps=0)
    servicer = MasterServicer(
        dispatcher, evaluation=evaluation, final_eval=True, epoch_end_eval=True
    )

    version = 0
    rounds_seen = 0
    for _ in range(200):
        resp = servicer.GetTask({"worker_id": "w0"})
        if resp["task"] is None:
            if resp["finished"]:
                break
            continue
        task = resp["task"]
        version += 1
        report = {
            "worker_id": "w0", "task_id": task["task_id"], "success": True,
            "task_type": task["type"], "model_version": version,
        }
        if task["type"] == TASK_EVALUATION:
            report["metrics"] = {"accuracy": 0.5}
            report["weight"] = 16.0
            del report["model_version"]
        servicer.ReportTaskResult(report)
    else:
        pytest.fail("job did not finish")
    rounds_seen = evaluation.completed_rounds()
    assert rounds_seen == 3  # one per epoch boundary, final included
    assert servicer.job_finished()


def test_log_level_flag_applies():
    from elasticdl_tpu.common import log_utils

    lg = log_utils.get_logger("test-flag-logger")
    log_utils.set_level("DEBUG")
    try:
        assert lg.level == logging.DEBUG
        # future loggers inherit the configured default
        lg2 = log_utils.get_logger("test-flag-logger-2")
        assert lg2.level == logging.DEBUG
    finally:
        log_utils.set_level("INFO")


def test_removed_flags_are_gone():
    import dataclasses

    names = {f.name for f in dataclasses.fields(JobConfig)}
    assert "num_ps_shards" not in names
    assert "use_tpu" not in names


def test_eval_ragged_tail_exact(tmp_path, devices):
    """The headline exactness check (VERDICT r2 task 7): eval metrics over a
    shard whose size does NOT divide the minibatch equal the unsharded
    values exactly — padded duplicates contribute nothing."""
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import Worker
    from elasticdl_tpu.master.task_dispatcher import Task

    n_records = 24  # minibatch 16 -> one full chunk + ragged tail of 8
    path, reader, _ = _mk_shards(tmp_path, n=n_records, per_task=n_records)
    config = JobConfig(
        model_def="mnist.model_spec", training_data=path, minibatch_size=16
    )
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )
    worker = Worker(
        config, master=None, reader=reader, spec=spec, devices=devices
    )
    worker._apply_membership(
        {"version": 0, "world_size": 1, "ranks": {"worker-0": 0}}, initial=True
    )
    worker.state = worker.trainer.init_state(jax.random.key(0))

    shard = Shard(name=path, start=0, end=n_records)
    task = Task(task_id=0, shard=shard, type=TASK_EVALUATION)
    got, weight = worker._run_evaluation_task(task)
    assert weight == n_records

    # Unsharded ground truth over the raw records.
    records = list(reader.read_records(shard))
    batch = spec.feed(records)
    params = jax.device_get(worker.state).params
    logits = spec.apply(params, batch, train=False)
    expected = {
        k: float(v) for k, v in spec.metrics(jnp.asarray(logits), batch).items()
    }
    for k in expected:
        np.testing.assert_allclose(got[k], expected[k], rtol=1e-5), k


def test_training_metrics_averaged(tmp_path, devices):
    """Training-task metrics are the mean over the task's minibatches, not
    just the last one's."""
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import Worker
    from elasticdl_tpu.master.task_dispatcher import Task

    path, reader, _ = _mk_shards(tmp_path, n=32, per_task=32)
    config = JobConfig(
        model_def="mnist.model_spec", training_data=path, minibatch_size=16
    )
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )
    worker = Worker(config, master=None, reader=reader, spec=spec, devices=devices)
    worker._apply_membership(
        {"version": 0, "world_size": 1, "ranks": {"worker-0": 0}}, initial=True
    )
    worker.state = worker.trainer.init_state(jax.random.key(0))

    seen = []
    orig_scan = worker.trainer.train_scan

    def spy_scan(state, stacked):
        state, metrics = orig_scan(state, stacked)
        arr = {k: np.asarray(v) for k, v in metrics.items()}
        n = next(iter(arr.values())).shape[0]
        for t in range(n):
            seen.append({k: float(v[t]) for k, v in arr.items()})
        return state, metrics

    worker.trainer.train_scan = spy_scan
    task = Task(task_id=0, shard=Shard(name=path, start=0, end=32))
    got = worker._run_training_task(task)
    # The fused path runs the task's 2 minibatches in one lax.scan; the
    # reported metrics must still be the mean over BOTH steps.
    assert len(seen) == 2
    for k in got:
        np.testing.assert_allclose(
            got[k], (seen[0][k] + seen[1][k]) / 2, rtol=1e-6
        )


def test_fused_scan_independent_of_prefetch_depth(tmp_path, devices):
    """--prefetch_depth=0 is a data-pipeline debugging knob; it must NOT
    silently revert the worker to per-step dispatch (VERDICT r4 Weak #4 —
    the fused-scan switch is its own flag, default on)."""
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import Worker
    from elasticdl_tpu.master.task_dispatcher import Task

    path, reader, _ = _mk_shards(tmp_path, n=32, per_task=32)
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )

    def _mk_worker(**cfg):
        config = JobConfig(
            model_def="mnist.model_spec",
            training_data=path,
            minibatch_size=16,
            **cfg,
        )
        worker = Worker(
            config, master=None, reader=reader, spec=spec, devices=devices
        )
        worker._apply_membership(
            {"version": 0, "world_size": 1, "ranks": {"worker-0": 0}},
            initial=True,
        )
        worker.state = worker.trainer.init_state(jax.random.key(0))
        return worker

    task = Task(task_id=0, shard=Shard(name=path, start=0, end=32))

    # prefetch disabled -> fused scan still used.
    worker = _mk_worker(prefetch_depth=0)
    calls = []
    orig = worker.trainer.train_scan
    worker.trainer.train_scan = lambda s, b: (calls.append(1), orig(s, b))[1]
    worker._run_training_task(task)
    assert calls, "fused scan must not depend on prefetch_depth"

    # fused scan disabled -> per-step dispatch, even with prefetch on.
    worker = _mk_worker(fused_task_scan=False, prefetch_depth=2)
    worker.trainer.train_scan = lambda *a: pytest.fail(
        "fused_task_scan=False must take the per-step path"
    )
    worker._run_training_task(task)


def test_dispatcher_stop_is_sticky(tmp_path):
    """After --max_steps stop(), failed/timed-out/recovered tasks must NOT
    requeue — requeueing would re-open dispatch past the limit."""
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    generate("mnist", str(tmp_path / "t.rio"), 64)
    shards = create_data_reader(str(tmp_path / "t.rio")).create_shards(16)
    clock = [0.0]
    d = TaskDispatcher(shards, num_epochs=10, task_timeout_s=5.0,
                       clock=lambda: clock[0])
    t1 = d.get_task("w0")
    t2 = d.get_task("w1")
    d.stop()
    assert d.counts()["todo"] == 0
    # failure after stop: dropped, not requeued
    d.report(t1.task_id, success=False)
    assert d.counts()["todo"] == 0
    # timeout after stop: released, not requeued
    clock[0] = 100.0
    assert d.get_task("w2") is None
    # dead-worker recovery after stop: released, not requeued
    d.recover_tasks("w1")
    assert d.counts()["todo"] == 0
    assert d.finished()


def test_eval_scan_matches_per_batch(tmp_path, devices):
    """Fused eval (lax.scan over full chunks) must reproduce the per-batch
    eval path's aggregated metrics exactly (incl. AUC histograms)."""
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import Worker
    from elasticdl_tpu.master.task_dispatcher import TASK_EVALUATION, Task

    path, reader, _ = _mk_shards(tmp_path, n=40, per_task=40)
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )

    def run(prefetch_depth):
        config = JobConfig(
            model_def="mnist.model_spec", training_data=path,
            minibatch_size=16, prefetch_depth=prefetch_depth,
        )
        worker = Worker(
            config, master=None, reader=reader, spec=spec, devices=devices
        )
        worker._apply_membership(
            {"version": 0, "world_size": 1, "ranks": {"w": 0}}, initial=True
        )
        worker.state = worker.trainer.init_state(jax.random.key(0))
        task = Task(
            task_id=0, shard=Shard(name=path, start=0, end=40),
            type=TASK_EVALUATION,
        )
        return worker._run_evaluation_task(task)

    fused_metrics, fused_total = run(prefetch_depth=2)   # scan + masked tail
    plain_metrics, plain_total = run(prefetch_depth=0)   # per-batch path
    assert fused_total == plain_total == 40
    assert set(fused_metrics) == set(plain_metrics)
    for k in fused_metrics:
        np.testing.assert_allclose(
            fused_metrics[k], plain_metrics[k], rtol=1e-6, atol=1e-9,
            err_msg=k,
        )
