"""Native host embedding store: optimizer numerics golden-tested against
numpy/optax references, duplicate-id accumulation, checkpoint round-trip, and
the native recordio scanner vs the Python indexer (the reference's Go PS
unit-test scope: optimizer math, KV ops, dump/load — SURVEY.md §4)."""

import numpy as np
import optax
import pytest

from elasticdl_tpu.data.recordio import RecordIOReader, write_records

pytest.importorskip("ctypes")
from elasticdl_tpu.ps.host_store import (  # noqa: E402
    HostEmbeddingStore,
    native_lib_available,
    recordio_index_native,
    recordio_verify_native,
)

pytestmark = pytest.mark.skipif(
    not native_lib_available(), reason="native lib failed to build"
)

DIM = 16


def test_pull_deterministic_init():
    s = HostEmbeddingStore(DIM, "sgd", init_scale=0.05)
    ids = np.array([5, 9, 5])
    rows = s.pull(ids)
    np.testing.assert_array_equal(rows[0], rows[2])  # same id, same row
    assert not np.array_equal(rows[0], rows[1])
    assert np.abs(rows).max() <= 0.05
    assert len(s) == 2
    # A second store created identically produces identical init.
    s2 = HostEmbeddingStore(DIM, "sgd", init_scale=0.05)
    np.testing.assert_array_equal(s2.pull(ids), rows)


def test_sgd_matches_numpy():
    lr = 0.1
    s = HostEmbeddingStore(DIM, "sgd", learning_rate=lr)
    ids = np.array([1, 2])
    w0 = s.pull(ids).copy()
    g = np.random.default_rng(0).normal(size=(2, DIM)).astype(np.float32)
    s.push_grad(ids, g)
    np.testing.assert_allclose(s.pull(ids), w0 - lr * g, rtol=1e-6)


def test_duplicate_ids_accumulate_before_apply():
    """Two grads for one id must be summed, then ONE optimizer step applied
    (matters for stateful optimizers: adagrad with two separate steps gives a
    different result than one accumulated step)."""
    lr = 0.5
    g1 = np.full((1, DIM), 0.3, np.float32)
    g2 = np.full((1, DIM), -0.1, np.float32)

    s = HostEmbeddingStore(DIM, "adagrad", learning_rate=lr, init_scale=0.0)
    s.push_grad(np.array([7, 7]), np.concatenate([g1, g2]))

    ref = HostEmbeddingStore(DIM, "adagrad", learning_rate=lr, init_scale=0.0)
    ref.push_grad(np.array([7]), g1 + g2)
    np.testing.assert_allclose(s.pull(np.array([7])), ref.pull(np.array([7])), rtol=1e-6)


def test_adam_matches_optax():
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    s = HostEmbeddingStore(
        DIM, "adam", learning_rate=lr, beta1=b1, beta2=b2, eps=eps, init_scale=0.0
    )
    ids = np.array([3])
    opt = optax.adam(lr, b1=b1, b2=b2, eps=eps)
    params = {"w": np.zeros((1, DIM), np.float32)}
    opt_state = opt.init(params)
    rng = np.random.default_rng(1)
    for _ in range(5):
        g = rng.normal(size=(1, DIM)).astype(np.float32)
        s.push_grad(ids, g)
        updates, opt_state = opt.update({"w": g}, opt_state, params)
        params = optax.apply_updates(params, updates)
    np.testing.assert_allclose(s.pull(ids)[0], params["w"][0], rtol=1e-4, atol=1e-6)


def test_momentum_matches_optax():
    lr, mom = 0.1, 0.9
    s = HostEmbeddingStore(
        DIM, "momentum", learning_rate=lr, momentum=mom, init_scale=0.0
    )
    ids = np.array([0])
    opt = optax.sgd(lr, momentum=mom)
    params = {"w": np.zeros((1, DIM), np.float32)}
    opt_state = opt.init(params)
    rng = np.random.default_rng(2)
    for _ in range(4):
        g = rng.normal(size=(1, DIM)).astype(np.float32)
        s.push_grad(ids, g)
        updates, opt_state = opt.update({"w": g}, opt_state, params)
        params = optax.apply_updates(params, updates)
    np.testing.assert_allclose(s.pull(ids)[0], params["w"][0], rtol=1e-5, atol=1e-7)


def test_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "store.bin")
    s = HostEmbeddingStore(DIM, "adam", learning_rate=0.01)
    ids = np.arange(100)
    s.pull(ids)
    s.push_grad(ids, np.ones((100, DIM), np.float32))
    assert s.save(path) == 100

    s2 = HostEmbeddingStore(DIM, "adam", learning_rate=0.01)
    assert s2.load(path) == 100
    np.testing.assert_array_equal(s2.pull(ids), s.pull(ids))
    # Post-restore training continues identically (slots restored too).
    g = np.full((100, DIM), 0.5, np.float32)
    s.push_grad(ids, g)
    s2.push_grad(ids, g)
    np.testing.assert_allclose(s2.pull(ids), s.pull(ids), rtol=1e-6)


def test_checkpoint_mismatch_rejected(tmp_path):
    path = str(tmp_path / "store.bin")
    s = HostEmbeddingStore(DIM, "adam")
    s.pull(np.array([1]))
    s.save(path)
    with pytest.raises(ValueError):
        HostEmbeddingStore(DIM, "sgd").load(path)


def test_native_recordio_scanner_matches_python(tmp_path):
    path = str(tmp_path / "d.rio")
    records = [bytes([i]) * (i * 7 % 50) for i in range(200)]
    write_records(path, records)
    py_offsets = RecordIOReader(path).index()
    native_offsets = recordio_index_native(path)
    np.testing.assert_array_equal(native_offsets, np.asarray(py_offsets))
    assert recordio_verify_native(path, native_offsets, 0, 200) == -1

    # Corrupt one payload byte (record 151 has a non-empty payload):
    # verify pinpoints the record.
    raw = bytearray(open(path, "rb").read())
    raw[native_offsets[151] + 8] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    assert recordio_verify_native(path, native_offsets, 0, 200) == 151


def test_native_f16_cast_matches_numpy():
    """The PRE-transform's f32->f16 cast must match numpy bit-for-bit —
    including NaN, which ADVICE r4 #2 found collapsing to inf (latent: the
    current log1p(max(x,0)) pipeline can't produce one, but the cast is a
    general utility and must not lie if the transform changes)."""
    import ctypes
    import math

    from elasticdl_tpu.ps import host_store

    lib = host_store._load()
    lib.edl_f32_to_f16.restype = ctypes.c_uint16
    lib.edl_f32_to_f16.argtypes = [ctypes.c_float]

    cases = np.array(
        [0.0, -0.0, 1.0, -1.0, 0.1, 65504.0, 65520.0, 1e9, -1e9,
         6e-5, 5.96e-8, 1e-10, math.inf, -math.inf, math.nan, -math.nan,
         2.0009765625, 2.001953125],  # exact-tie rounding cases
        dtype=np.float32,
    )
    rng = np.random.default_rng(0)
    cases = np.concatenate(
        [cases, rng.standard_normal(500).astype(np.float32) * 1e3]
    )
    expected = cases.astype(np.float16).view(np.uint16)
    got = np.array(
        [lib.edl_f32_to_f16(float(v)) for v in cases], dtype=np.uint16
    )
    # NaN payloads may differ; require NaN-ness, exact bits elsewhere.
    nan_mask = np.isnan(cases)
    np.testing.assert_array_equal(got[~nan_mask], expected[~nan_mask])
    assert all(
        (g & 0x7C00) == 0x7C00 and (g & 0x03FF) != 0 for g in got[nan_mask]
    )
