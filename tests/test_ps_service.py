"""PS service tier (ps/service.py): the native host store behind gRPC.

Covers the wire codec, shard routing, numerics-vs-local-store equivalence,
checkpoint fan-out (each shard dumps its own slice), the trainer swapping in
RemoteEmbeddingStore (config.ps_addresses), and the master launching/awaiting
a real PS pod fleet end-to-end.
"""

import os

import numpy as np
import pytest

from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
from elasticdl_tpu.models.spec import HostTableIO
# Canonical public import path for the service tier classes:
from elasticdl_tpu.ps import PSClient, PSServer, RemoteEmbeddingStore  # noqa: F401
from elasticdl_tpu.ps.service import (
    PSFrameError,
    decode_frame,
    encode_frame,
    parse_ps_addresses,
    shard_of,
    snapshot_filename,
    validate_meta,
)


def _native_available() -> bool:
    from elasticdl_tpu.ps.host_store import native_lib_available

    return native_lib_available()


needs_native = pytest.mark.skipif(
    not _native_available(), reason="native lib unavailable"
)

IO = HostTableIO(
    ids_fn=lambda b: b["cat"], dim=8, optimizer="sgd", learning_rate=0.5
)


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------


def test_frame_roundtrip():
    meta = {"table": "t", "nested": {"a": [1, 2]}}
    arrays = {
        "ids": np.arange(7, dtype=np.int64),
        "rows": np.random.RandomState(0).randn(7, 8).astype(np.float32),
        "empty": np.empty((0, 3), np.float32),
    }
    meta2, arrays2 = decode_frame(encode_frame(meta, arrays))
    assert meta2 == meta
    assert set(arrays2) == set(arrays)
    for k in arrays:
        np.testing.assert_array_equal(arrays2[k], arrays[k])
        assert arrays2[k].dtype == arrays[k].dtype


def test_frame_malformed_fails_at_boundary():
    with pytest.raises(PSFrameError):
        decode_frame(b"\x01")  # too short
    with pytest.raises(PSFrameError):
        decode_frame(b"\xff\xff\xff\xff")  # header runs past payload
    good = encode_frame({"table": "t"}, {"ids": np.arange(3, dtype=np.int64)})
    with pytest.raises(PSFrameError):
        decode_frame(good[:-4])  # truncated array payload
    with pytest.raises(PSFrameError):
        validate_meta("Pull", {})  # missing required field
    with pytest.raises(PSFrameError):
        validate_meta("Pull", {"table": 3})  # wrong type
    with pytest.raises(PSFrameError):
        validate_meta("Nope", {})  # unknown method


def test_shard_of_nonnegative_for_negative_ids():
    ids = np.array([-7, -1, 0, 5, 1 << 60], dtype=np.int64)
    owner = shard_of(ids, 4)
    assert ((owner >= 0) & (owner < 4)).all()


# ---------------------------------------------------------------------------
# server + client
# ---------------------------------------------------------------------------


@pytest.fixture
def one_shard():
    server = PSServer({"t": IO}, shard=0, num_shards=1).start()
    store = RemoteEmbeddingStore("t", IO.dim, [server.address])
    store.wait_ready()
    yield server, store
    store.close()
    server.stop()


@needs_native
def test_remote_matches_local_store(one_shard):
    """Pull/push through the service == the same ops on a local store:
    deterministic per-id init plus identical server-side optimizer applies."""
    from elasticdl_tpu.ps.host_store import HostEmbeddingStore

    _, remote = one_shard
    local = HostEmbeddingStore(
        dim=IO.dim, optimizer=IO.optimizer, learning_rate=IO.learning_rate,
        init_scale=IO.init_scale,
    )
    ids = np.array([[3, 9, 3], [7, 1, 9]], dtype=np.int64)  # dups included
    np.testing.assert_array_equal(remote.pull(ids), local.pull(ids))

    grads = np.random.RandomState(1).randn(*ids.shape, IO.dim).astype(np.float32)
    remote.push_grad(ids, grads)
    local.push_grad(ids, grads)
    np.testing.assert_array_equal(remote.pull(ids), local.pull(ids))
    assert len(remote) == len(local) == 4  # distinct ids materialized


@needs_native
def test_sharded_routing_and_stats():
    """ids route by id mod n; values match a single-shard fleet exactly
    (per-id determinism makes topology invisible to the caller)."""
    servers = [
        PSServer({"t": IO}, shard=s, num_shards=2).start() for s in range(2)
    ]
    both = RemoteEmbeddingStore("t", IO.dim, [s.address for s in servers])
    solo_server = PSServer({"t": IO}, shard=0, num_shards=1).start()
    solo = RemoteEmbeddingStore("t", IO.dim, [solo_server.address])
    try:
        ids = np.array([0, 1, 2, 3, 4, 5, 6, 101], dtype=np.int64)
        np.testing.assert_array_equal(both.pull(ids), solo.pull(ids))
        g = np.random.RandomState(2).randn(ids.size, IO.dim).astype(np.float32)
        both.push_grad(ids, g)
        solo.push_grad(ids, g)
        np.testing.assert_array_equal(both.pull(ids), solo.pull(ids))
        # evens (incl. 0,2,4,6) on shard 0, odds (1,3,5,101) on shard 1
        meta0, _ = both._clients[0].call("Stats", {})
        meta1, _ = both._clients[1].call("Stats", {})
        assert meta0["tables"]["t"] == 4
        assert meta1["tables"]["t"] == 4
        assert meta0["shard"] == 0 and meta0["num_shards"] == 2
    finally:
        both.close()
        solo.close()
        for s in servers + [solo_server]:
            s.stop()


@needs_native
def test_unknown_table_and_bad_arrays_are_invalid_argument(one_shard):
    import grpc

    _, remote = one_shard
    client = remote._clients[0]
    with pytest.raises(grpc.RpcError) as e:
        client.call("Pull", {"table": "nope"}, {"ids": np.arange(2, dtype=np.int64)})
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    with pytest.raises(grpc.RpcError) as e:
        client.call("Pull", {"table": "t"}, {"ids": np.arange(2, dtype=np.int32)})
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    with pytest.raises(grpc.RpcError) as e:
        client.call(
            "PushGrad", {"table": "t"},
            {"ids": np.arange(2, dtype=np.int64),
             "grads": np.zeros((3, IO.dim), np.float32)},  # shape mismatch
        )
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


# ---------------------------------------------------------------------------
# checkpoint fan-out
# ---------------------------------------------------------------------------


@needs_native
def test_snapshot_save_load_across_restart(tmp_path):
    """Each shard dumps its own slice; a restarted fleet restores rows
    exactly; restore_latest picks the newest COMPLETE step."""
    servers = [
        PSServer({"t": IO}, shard=s, num_shards=2).start() for s in range(2)
    ]
    store = RemoteEmbeddingStore("t", IO.dim, [s.address for s in servers])
    ids = np.arange(10, dtype=np.int64)
    g = np.random.RandomState(3).randn(ids.size, IO.dim).astype(np.float32)
    store.push_grad(ids, g)
    before = store.pull(ids)
    store.save_snapshot(str(tmp_path), step=5)
    for s in range(2):
        assert os.path.exists(
            tmp_path / "host_stores" / "5" / snapshot_filename("t", s, 2)
        )
    store.close()
    for s in servers:
        s.stop()

    fresh = [
        PSServer({"t": IO}, shard=s, num_shards=2) for s in range(2)
    ]
    assert [s.restore_latest(str(tmp_path)) for s in fresh] == [5, 5]
    for s in fresh:
        s.start()
    store2 = RemoteEmbeddingStore("t", IO.dim, [s.address for s in fresh])
    np.testing.assert_array_equal(store2.pull(ids), before)
    store2.close()
    for s in fresh:
        s.stop()


@needs_native
def test_restore_latest_skips_torn_step(tmp_path):
    """A step missing this shard's file is skipped for an older intact one;
    load(strict=True) on the torn step aborts with FAILED_PRECONDITION-level
    structured error at the client."""
    server = PSServer({"t": IO}, shard=0, num_shards=1).start()
    store = RemoteEmbeddingStore("t", IO.dim, [server.address])
    ids = np.arange(4, dtype=np.int64)
    store.push_grad(ids, np.ones((4, IO.dim), np.float32))
    rows_at_2 = store.pull(ids)
    store.save_snapshot(str(tmp_path), step=2)
    # Fabricate a TORN newer step: dir exists, shard file missing.
    os.makedirs(tmp_path / "host_stores" / "9")
    assert not store.load_snapshot(str(tmp_path), step=9, strict=False)
    with pytest.raises(FileNotFoundError):
        store.load_snapshot(str(tmp_path), step=9, strict=True)
    store.close()
    server.stop()

    fresh = PSServer({"t": IO}, shard=0, num_shards=1)
    assert fresh.restore_latest(str(tmp_path)) == 2
    fresh.start()
    store2 = RemoteEmbeddingStore("t", IO.dim, [fresh.address])
    np.testing.assert_array_equal(store2.pull(ids), rows_at_2)
    store2.close()
    fresh.stop()


@needs_native
def test_snapshot_retention_prunes_per_shard(tmp_path):
    server = PSServer({"t": IO}, shard=0, num_shards=1).start()
    store = RemoteEmbeddingStore("t", IO.dim, [server.address])
    store.pull(np.arange(3, dtype=np.int64))
    for step in (1, 2, 3, 4, 5):
        store.save_snapshot(str(tmp_path), step=step, keep_max=3)
    kept = sorted(os.listdir(tmp_path / "host_stores"))
    assert kept == ["3", "4", "5"]
    store.close()
    server.stop()


# ---------------------------------------------------------------------------
# trainer integration: remote stores via config.ps_addresses
# ---------------------------------------------------------------------------


@needs_native
def test_trainer_uses_remote_stores_and_matches_local(devices):
    """A host-tier DeepFM trained against the PS service tracks the
    local-store run bit-for-bit (same seed, same batches, same server-side
    optimizer), proving the RPC hop changes nothing numerically."""
    import jax

    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    spec = load_model_spec(
        "elasticdl_tpu.models", "deepfm.model_spec",
        buckets_per_feature=64, embedding_dim=8, hidden=(16,),
        host_tier=True, compute_dtype="float32",
    )
    assert spec.host_io
    server = PSServer(spec.host_io, shard=0, num_shards=1).start()
    mesh = create_mesh(devices[:4])

    def run(config):
        trainer = Trainer(spec, config, mesh)
        state = trainer.init_state(jax.random.key(0))
        losses = []
        rng = np.random.RandomState(0)
        for _ in range(3):
            batch = {
                "dense": rng.rand(16, 13).astype(np.float32) * 100,
                "cat": rng.randint(0, 1 << 20, (16, 26)).astype(np.int64),
                "labels": rng.randint(0, 2, (16,)).astype(np.int32),
            }
            state, metrics = trainer.run_train_step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses, trainer

    base = JobConfig(distribution_strategy=DistributionStrategy.PARAMETER_SERVER)
    remote_cfg = JobConfig(
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        ps_addresses=server.address,
    )
    try:
        local_losses, local_trainer = run(base)
        remote_losses, remote_trainer = run(remote_cfg)
        assert remote_trainer._remote_ps and not local_trainer._remote_ps
        assert remote_losses == local_losses
        assert all(np.isfinite(remote_losses))
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# master-orchestrated end-to-end: PS pod fleet + worker subprocess
# ---------------------------------------------------------------------------

WORKER_PY = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
from elasticdl_tpu.worker.main import main
sys.exit(main())
"""

PS_PY = """
import sys
sys.path.insert(0, {repo!r})
from elasticdl_tpu.ps.main import main
sys.exit(main())
"""


@needs_native
@pytest.mark.slow
def test_master_launches_ps_fleet_end_to_end(tmp_path):
    """`--num_ps_pods 2`: the master picks ports, launches two PS shard
    subprocesses, waits for readiness, hands workers the addresses through
    the config bus, the host-tier DeepFM job trains to completion, and the
    final checkpoint leaves every shard's slice on disk."""
    import sys as _sys

    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.main import Master
    from elasticdl_tpu.master.pod_manager import ProcessPodBackend

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker_entry = tmp_path / "worker_entry.py"
    worker_entry.write_text(WORKER_PY.format(repo=repo))
    ps_entry = tmp_path / "ps_entry.py"
    ps_entry.write_text(PS_PY.format(repo=repo))

    data = str(tmp_path / "criteo.rio")
    generate("criteo", data, 64)
    config = JobConfig(
        job_name="psjob",
        model_def="deepfm.model_spec",
        model_params=(
            'buckets_per_feature=64;embedding_dim=8;hidden=[16];'
            'host_tier=true;compute_dtype="float32"'
        ),
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        training_data=data,
        minibatch_size=16,
        num_minibatches_per_task=1,
        num_workers=1,
        num_ps_pods=2,
        checkpoint_steps=2,
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    master = Master(
        config,
        pod_backend=ProcessPodBackend(argv=[_sys.executable, str(worker_entry)]),
        ps_backend=ProcessPodBackend(argv=[_sys.executable, str(ps_entry)]),
    )
    assert len(parse_ps_addresses(config.ps_addresses)) == 2
    status = master.run(poll_interval_s=0.1)
    assert status["finished"]
    assert status["done"] == 4  # 64 records / 16-record tasks

    # Final checkpoint: BOTH shards dumped their slice of the host table.
    root = tmp_path / "ckpt" / "host_stores"
    steps = sorted(os.listdir(root), key=int)
    assert steps, "no host-store snapshot written"
    latest = root / steps[-1]
    from elasticdl_tpu.models.deepfm import HOST_FM_KEY

    for s in range(2):
        assert (latest / snapshot_filename(HOST_FM_KEY, s, 2)).exists()


@needs_native
@pytest.mark.slow
def test_two_process_world_trains_against_ps_fleet(tmp_path):
    """THE multi-process host-tier proof: two real worker processes form one
    jax.distributed world (8-device mesh) and train a host-tier DeepFM
    against a shared 2-shard PS fleet.  Exercises the per-process slice pull
    (_local_example_range), the addressable-shards-only cotangent push, and
    the rank-gated snapshot fan-out — none of which run outside a real
    multi-process world."""
    import signal
    import subprocess
    import sys as _sys
    import threading
    import time

    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.rendezvous import RendezvousServer
    from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.worker.worker import RESTART_EXIT_CODE

    data = str(tmp_path / "criteo.rio")
    generate("criteo", data, 128)
    reader = create_data_reader(data)
    shards = reader.create_shards(32)

    dispatcher = TaskDispatcher(shards, num_epochs=2)
    rendezvous = RendezvousServer(heartbeat_timeout_s=6.0)
    servicer = MasterServicer(dispatcher, rendezvous=rendezvous)
    server = MasterServer(servicer, port=0).start()
    stop = threading.Event()

    max_world = {"n": 0}

    def reap():
        while not stop.is_set():
            rendezvous.reap_dead()
            max_world["n"] = max(
                max_world["n"], rendezvous.membership()["world_size"]
            )
            time.sleep(0.25)

    threading.Thread(target=reap, daemon=True).start()

    # ONE source of truth for the model shape: the PS fleet builds its
    # stores from the spec parsed out of the same string the workers get.
    model_params = (
        'buckets_per_feature=64;embedding_dim=8;hidden=[16];'
        'host_tier=true;compute_dtype="float32"'
    )
    from elasticdl_tpu.common.config import _parse_kv_string
    from elasticdl_tpu.models.spec import load_model_spec

    spec = load_model_spec(
        "elasticdl_tpu.models", "deepfm.model_spec",
        **_parse_kv_string(model_params),
    )
    ps_servers = [
        PSServer(spec.host_io, shard=s, num_shards=2).start() for s in range(2)
    ]

    import socket as _socket

    coord = _socket.socket()
    coord.bind(("", 0))
    coord_port = coord.getsockname()[1]
    coord.close()

    config = JobConfig(
        model_def="deepfm.model_spec",
        model_params=model_params,
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        training_data=data,
        minibatch_size=16,
        master_addr=server.address,
        multihost=True,
        coordinator_port=coord_port,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_steps=4,
        num_epochs=2,
        ps_addresses=",".join(s.address for s in ps_servers),
    )

    def _spawn(worker_id):
        env = dict(os.environ)
        env.update(config.to_env())
        env["ELASTICDL_WORKER_ID"] = worker_id
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        log = open(tmp_path / f"{worker_id}.log", "w")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        return subprocess.Popen(
            [_sys.executable, "-m", "elasticdl_tpu.worker.main"],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=repo,
        )

    def _log_tail(w):
        return open(tmp_path / f"{w}.log").read()[-3000:]

    procs = {}
    relaunches = {"n": 0}
    try:
        procs.update({w: _spawn(w) for w in ("w-a", "w-b")})
        deadline = time.time() + 420
        while time.time() < deadline:
            if servicer.JobStatus({})["finished"]:
                break
            for w, p in list(procs.items()):
                rc = p.poll()
                if rc is None:
                    continue
                if rc == 0:
                    procs.pop(w)
                    continue
                fatal = (
                    "JAX distributed service detected fatal errors"
                    in _log_tail(w)
                )
                if rc == RESTART_EXIT_CODE or fatal:
                    assert relaunches["n"] < 8, (
                        f"{w} restart churn; log:\n" + _log_tail(w)
                    )
                    relaunches["n"] += 1
                    procs[w] = _spawn(w)
                else:
                    pytest.fail(f"{w} exited rc={rc}; log:\n" + _log_tail(w))
            time.sleep(0.5)
        status = servicer.JobStatus({})
        assert status["finished"], (
            f"job did not finish: {status}; logs:\n"
            + "".join(_log_tail(w) for w in ("w-a", "w-b"))
        )
        # The proof is only multi-process if the world really reached 2.
        assert max_world["n"] == 2, f"world never formed (max {max_world})"
        # Both shards served pulls and took pushes: rows materialized.
        sizes = []
        for s in ps_servers:
            meta, _ = s._stats({}, {})
            sizes.append(meta["tables"][list(spec.host_io)[0]])
        assert all(n > 0 for n in sizes), f"shard sizes {sizes}"
        # Rank 0's final checkpoint fanned a Save out: per-shard files exist.
        root = tmp_path / "ckpt" / "host_stores"
        steps = sorted(os.listdir(root), key=int)
        assert steps, "no PS snapshot written"
        key = list(spec.host_io)[0]
        for s in range(2):
            assert (root / steps[-1] / snapshot_filename(key, s, 2)).exists()
    finally:
        stop.set()
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for s in ps_servers:
            s.stop()
        server.stop()


@needs_native
@pytest.mark.slow
def test_ps_pod_crash_relaunch_restores_and_job_finishes(tmp_path):
    """Chaos: SIGKILL a PS shard mid-job.  The master's relaunch policy
    restarts it on the SAME port, the relaunched pod restores its slice from
    the newest snapshot (ps/main.py), the workers' RemoteEmbeddingStore
    retry bridges the outage, and the job drains to completion."""
    import signal
    import sys as _sys
    import threading
    import time

    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.main import Master
    from elasticdl_tpu.master.pod_manager import ProcessPodBackend

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker_entry = tmp_path / "worker_entry.py"
    worker_entry.write_text(WORKER_PY.format(repo=repo))
    ps_entry = tmp_path / "ps_entry.py"
    ps_entry.write_text(PS_PY.format(repo=repo))

    data = str(tmp_path / "criteo.rio")
    generate("criteo", data, 128)
    config = JobConfig(
        job_name="pschaos",
        model_def="deepfm.model_spec",
        model_params=(
            'buckets_per_feature=64;embedding_dim=8;hidden=[16];'
            'host_tier=true;compute_dtype="float32"'
        ),
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        training_data=data,
        minibatch_size=16,
        num_minibatches_per_task=1,
        num_workers=1,
        num_ps_pods=1,
        num_epochs=3,
        checkpoint_steps=2,
        checkpoint_dir=str(tmp_path / "ckpt"),
        max_worker_relaunch=4,
    )
    ps_backend = ProcessPodBackend(argv=[_sys.executable, str(ps_entry)])
    master = Master(
        config,
        pod_backend=ProcessPodBackend(argv=[_sys.executable, str(worker_entry)]),
        ps_backend=ps_backend,
    )
    result = {}

    def run():
        result["status"] = master.run(poll_interval_s=0.1)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    try:
        # Wait for the first host-store snapshot, then kill the PS shard.
        root = tmp_path / "ckpt" / "host_stores"
        deadline = time.time() + 120
        while time.time() < deadline and not (
            root.exists() and os.listdir(root)
        ):
            time.sleep(0.2)
        assert root.exists() and os.listdir(root), "no snapshot before kill"
        pid = ps_backend.pid("pschaos-ps-0")
        assert pid is not None, "PS pod not running"
        os.kill(pid, signal.SIGKILL)

        t.join(timeout=240)
        assert not t.is_alive(), "job did not finish after PS crash"
        assert result["status"]["finished"], result["status"]
        assert result["status"]["done"] == 24  # 8 tasks x 3 epochs
        # The relaunched shard really is a second generation of the slot.
        relaunched = master.ps_manager.pod_info("pschaos-ps-0-r1")
        assert relaunched is not None, "PS pod was not relaunched"
    finally:
        master.shutdown()


def test_parse_ps_addresses():
    assert parse_ps_addresses("a:1, b:2 ,,c:3") == ["a:1", "b:2", "c:3"]
    assert parse_ps_addresses("") == []


@needs_native
def test_multiprocess_host_tier_without_ps_raises(devices, monkeypatch):
    """Multi-process mesh + host tables + no PS fleet is the one illegal
    layout (each process would train divergent row copies): the constructor
    refuses with a message pointing at --num_ps_pods.  With ps_addresses
    set, the same construction succeeds with remote stores."""
    import elasticdl_tpu.parallel.trainer as trainer_mod
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh

    spec = load_model_spec(
        "elasticdl_tpu.models", "deepfm.model_spec",
        buckets_per_feature=64, embedding_dim=8, hidden=(16,),
        host_tier=True, compute_dtype="float32",
    )
    mesh = create_mesh(devices[:2])
    monkeypatch.setattr(trainer_mod, "_process_count", lambda m: 2)
    with pytest.raises(NotImplementedError, match="num_ps_pods"):
        trainer_mod.Trainer(
            spec,
            JobConfig(
                distribution_strategy=DistributionStrategy.PARAMETER_SERVER
            ),
            mesh,
        )
    server = PSServer(spec.host_io, shard=0, num_shards=1).start()
    try:
        t = trainer_mod.Trainer(
            spec,
            JobConfig(
                distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
                ps_addresses=server.address,
            ),
            mesh,
        )
        assert t._remote_ps
    finally:
        server.stop()


@needs_native
def test_concurrent_pulls_correct_under_contention(one_shard):
    """Per-table RW locking: many reader threads pulling EXISTING rows run
    concurrently with a pusher mutating other rows; every pull must return
    internally consistent rows (the pre-r4 global mutex made this trivially
    true but serialized the executor — this pins correctness of the
    concurrent path)."""
    import threading

    from elasticdl_tpu.ps.host_store import HostEmbeddingStore

    _, remote = one_shard
    read_ids = np.arange(0, 256, dtype=np.int64)
    write_ids = np.arange(1000, 1256, dtype=np.int64)
    baseline = remote.pull(read_ids)  # materialize the read set

    stop = threading.Event()
    errors = []

    def reader():
        try:
            while not stop.is_set():
                rows = remote.pull(read_ids)
                # read rows are NEVER pushed to: must equal their init values
                np.testing.assert_array_equal(rows, baseline)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    local = HostEmbeddingStore(
        dim=IO.dim, optimizer=IO.optimizer, learning_rate=IO.learning_rate,
        init_scale=IO.init_scale,
    )
    rng = np.random.RandomState(7)
    for _ in range(30):
        g = rng.randn(write_ids.size, IO.dim).astype(np.float32)
        remote.push_grad(write_ids, g)
        local.push_grad(write_ids, g)
    stop.set()
    for t in threads:
        t.join()
    assert not errors, errors[0]
    np.testing.assert_array_equal(remote.pull(write_ids), local.pull(write_ids))


@needs_native
def test_stats_reports_restored_step(tmp_path):
    """Stats surfaces restored_step; RemoteEmbeddingStore.restored_steps
    collects it fleet-wide (the torn-fleet guard's wire half)."""
    server = PSServer({"t": IO}, shard=0, num_shards=1).start()
    store = RemoteEmbeddingStore("t", IO.dim, [server.address])
    store.wait_ready()
    try:
        assert store.restored_steps() == [None]
        store.pull(np.arange(8, dtype=np.int64))
        store.save_snapshot(str(tmp_path), step=12)
        store.load_snapshot(str(tmp_path), step=12)
        assert store.restored_steps() == [12]
    finally:
        store.close()
        server.stop()


@needs_native
def test_eval_job_fails_loud_on_fresh_or_divergent_ps_fleet(tmp_path, devices):
    """ADVICE r3 medium: an evaluation job must refuse a PS fleet that
    restored nothing (fresh rows) or restored DIVERGENT steps; a training
    job error-logs and continues."""
    import jax

    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    servers = [
        PSServer({"__host__fm_table": IO}, shard=s, num_shards=2).start()
        for s in range(2)
    ]
    addrs = ",".join(s.address for s in servers)
    try:
        def make_trainer(job_type):
            spec = load_model_spec(
                "elasticdl_tpu.models",
                "deepfm.model_spec",
                buckets_per_feature=64,
                embedding_dim=IO.dim - 1,
                hidden=(8,),
                host_tier=True,
            )
            config = JobConfig(
                distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
                job_type=job_type,
                ps_addresses=addrs,
            )
            return Trainer(spec, config, create_mesh(devices[:1]))

        # Fresh fleet: evaluation refuses, training proceeds.
        with pytest.raises(RuntimeError, match="no PS shard restored"):
            make_trainer("evaluation").restore_host_stores(str(tmp_path), 5)
        assert make_trainer("training").restore_host_stores(str(tmp_path), 5)

        # Divergent fleet: save a snapshot, then make only shard 0 load it.
        store = RemoteEmbeddingStore(
            "__host__fm_table", IO.dim, [s.address for s in servers]
        )
        store.wait_ready()
        store.pull(np.arange(32, dtype=np.int64))
        store.save_snapshot(str(tmp_path), step=7)
        servers[0]._load(
            {"directory": str(tmp_path), "step": 7, "strict": True}, {}
        )
        store.close()
        with pytest.raises(RuntimeError, match="divergent"):
            make_trainer("evaluation").restore_host_stores(str(tmp_path), 7)
        assert make_trainer("training").restore_host_stores(str(tmp_path), 7)
    finally:
        for s in servers:
            s.stop()
