"""Gang-formation settle protocol (worker.main.settle_membership).

The pod-event recovery bench (tools/rendezvous_bench.py pod) measured 54 s
of restart churn when staggered relaunches formed worlds one member at a
time or with stale incarnations; the settle gates (desired size + per-
member version confirmation) fixed it.  These tests drive the extracted
loop against the REAL RendezvousServer with scripted peer actions and a
virtual clock.
"""

from __future__ import annotations

import pytest

from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.worker.main import settle_membership


class _DirectMaster:
    """Master adapter: the two RPCs the settle loop uses, in-process."""

    def __init__(self, rdzv: RendezvousServer, fail: set | None = None):
        self.r = rdzv
        self.fail = fail or set()  # step numbers whose RPCs raise
        self.step = 0

    def call(self, method, req):
        if self.step in self.fail:
            raise ConnectionError("master briefly unreachable")
        if method == "Heartbeat":
            return {
                "version": self.r.heartbeat(
                    req["worker_id"], req.get("version")
                )
            }
        if method == "GetMembership":
            return self.r.membership()
        raise AssertionError(method)


def _drive(rdzv, worker_id, actions, fail=None, max_s=50.0, expected_ok=True):
    """Run settle_membership with a virtual clock; ``actions`` maps a sleep
    step number to a callable performing peer activity."""
    master = _DirectMaster(rdzv, fail=fail)
    t = [0.0]
    steps = [0]

    def clock():
        return t[0]

    def sleep(dt):
        steps[0] += 1
        master.step = steps[0]
        t[0] += max(dt, 0.05)
        fn = actions.get(steps[0])
        if fn:
            fn()

    view = settle_membership(
        master,
        worker_id,
        rdzv.membership(),
        poll_s=0.05,
        stable_s=1.0,
        max_s=max_s,
        clock=clock,
        sleep=sleep,
    )
    return view, t[0], steps[0]


def test_waits_for_full_confirmed_gang():
    r = RendezvousServer()
    r.set_expected(2)
    r.register("A", "hostA:1")
    # B joins only at sleep step 3; B's registration confirms the new
    # version for B, and A's own versioned heartbeat confirms it for A.
    view, elapsed, steps = _drive(
        r, "A", {3: lambda: r.register("B", "hostB:1")}
    )
    assert view["world_size"] == 2
    assert sorted(view["workers"]) == ["A", "B"]
    assert all(
        view["confirmed"][w] == view["version"] for w in view["workers"]
    )
    assert steps >= 3  # did NOT form a world of 1 while alone
    assert elapsed < 10  # and did not ride to the deadline


def test_stale_incarnation_blocks_formation_until_replaced():
    r = RendezvousServer()
    r.set_expected(2)
    r.register("stale", "h1:1")   # confirmed v1
    r.register("A", "h2:1")       # confirmed v2; stale never re-confirms
    view, elapsed, _ = _drive(
        r, "A",
        {
            4: lambda: r.remove("stale"),          # its restart exits
            6: lambda: r.register("B", "h1:2"),    # fresh incarnation
        },
    )
    assert sorted(view["workers"]) == ["A", "B"]
    assert "stale" not in view["workers"]
    assert all(
        view["confirmed"][w] == view["version"] for w in view["workers"]
    )
    assert elapsed < 10


def test_deadline_degrades_instead_of_wedging():
    r = RendezvousServer()
    r.set_expected(3)  # third member never arrives (crash loop)
    r.register("A", "h1:1")
    r.register("B", "h2:1")
    view, elapsed, _ = _drive(r, "A", {}, max_s=5.0)
    assert view["world_size"] == 2  # proceeds with who is present
    assert elapsed >= 5.0


def test_no_expected_falls_back_to_version_stability():
    r = RendezvousServer()  # expected stays 0: hand-spawned workers
    r.register("A", "h1:1")
    view, elapsed, _ = _drive(r, "A", {})
    assert view["world_size"] == 1
    assert 1.0 <= elapsed < 5.0  # stable_s wait, not the full deadline


def test_master_blips_are_retried():
    r = RendezvousServer()
    r.set_expected(2)
    r.register("A", "h1:1")
    view, elapsed, _ = _drive(
        r, "A",
        {2: lambda: r.register("B", "h2:1")},
        fail={1, 3, 4},  # RPCs raise on these polls
    )
    assert view["world_size"] == 2
    assert all(
        view["confirmed"][w] == view["version"] for w in view["workers"]
    )


def test_scale_down_waits_for_doomed_members_to_drain():
    """Scale-down window: desired size drops to 2 while the 2 doomed
    members are still registered (terminate grace).  Forming the 4-member
    world would guarantee an immediate re-collapse as they exit — the gate
    requires EXACT size, so formation waits for the drain."""
    r = RendezvousServer()
    r.set_expected(4)
    for w, h in (("A", "h1:1"), ("B", "h2:1"), ("C", "h3:1"), ("D", "h4:1")):
        r.register(w, h)
    r.set_expected(2)  # scale-down begins; C and D are being torn down
    # Everyone still heartbeats the current version during the grace.
    for w in "BCD":
        r.heartbeat(w, r.membership()["version"])
    view, elapsed, steps = _drive(
        r, "A",
        {
            3: lambda: r.remove("C"),
            5: lambda: (
                r.remove("D"),
                r.heartbeat("B", r.membership()["version"]),
            ),
        },
    )
    assert sorted(view["workers"]) == ["A", "B"]
    assert view["world_size"] == 2
    assert steps >= 5  # did NOT form the oversized 4-member world
    assert elapsed < 10
