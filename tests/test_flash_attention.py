"""Pallas flash attention vs the XLA reference oracle — forward and VJP.

Runs the REAL kernel in pallas interpret mode on the CPU harness (one code
path everywhere; the chip runs the same kernel compiled).  The oracle is
``ops.ring_attention.attention_reference`` — the numerics standard the ring
path is also tested against.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.ops.flash_attention import flash_attention
from elasticdl_tpu.ops.ring_attention import attention_reference


def _qkv(dtype, b=2, l=256, h=2, d=64, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, l, h, d)
    return tuple(
        (jax.random.normal(k, shape) * 0.5).astype(dtype) for k in ks
    )


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference_f32(causal):
    q, k, v = _qkv(jnp.float32)
    out = flash_attention(q, k, v, causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference_bf16(causal):
    q, k, v = _qkv(jnp.bfloat16)
    out = flash_attention(q, k, v, causal)
    ref = attention_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=causal,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2, rtol=3e-2
    )


@pytest.mark.parametrize("causal", [False, True])
def test_vjp_matches_reference(causal):
    q, k, v = _qkv(jnp.float32, b=1, l=128, h=2, d=64, seed=3)
    cot = jax.random.normal(jax.random.key(9), q.shape, jnp.float32)

    def loss_flash(q, k, v):
        return jnp.vdot(flash_attention(q, k, v, causal), cot)

    def loss_ref(q, k, v):
        return jnp.vdot(attention_reference(q, k, v, causal=causal), cot)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            gf, gr, atol=5e-5, rtol=5e-5, err_msg=f"d{name}"
        )


def test_shape_contract_fails_loud():
    q, k, v = _qkv(jnp.float32, l=200)  # not a TQ multiple
    with pytest.raises(ValueError, match="flash_attention supports"):
        flash_attention(q, k, v, True)
