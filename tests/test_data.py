"""Data layer: recordio format round-trip, shard range addressing, codec
round-trips, and end-to-end synthetic-file -> feed -> train-step for each
model family (the reference's data-reader unit tests, SURVEY.md §4)."""

import numpy as np
import pytest

from elasticdl_tpu.data import codecs, synthetic
from elasticdl_tpu.data.reader import (
    CSVDataReader,
    RecordIODataReader,
    Shard,
    create_data_reader,
)
from elasticdl_tpu.data.recordio import RecordIOReader, write_records


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "data.rio")
    records = [b"hello", b"", b"x" * 10_000, bytes(range(256))]
    assert write_records(path, records) == 4
    reader = RecordIOReader(path)
    assert len(reader) == 4
    assert list(reader.read_range(0, 4)) == records
    assert list(reader.read_range(1, 3)) == records[1:3]
    assert list(reader.read_range(3, 99)) == records[3:]


def test_recordio_crc_detects_corruption(tmp_path):
    path = str(tmp_path / "data.rio")
    write_records(path, [b"payload-one", b"payload-two"])
    raw = bytearray(open(path, "rb").read())
    raw[-3] ^= 0xFF  # flip a byte inside the last payload
    open(path, "wb").write(bytes(raw))
    reader = RecordIOReader(path)
    with pytest.raises(IOError):
        list(reader.read_range(0, 2))


def test_recordio_reader_shards(tmp_path):
    path = str(tmp_path / "d.rio")
    write_records(path, [b"r%d" % i for i in range(25)])
    reader = RecordIODataReader(path)
    shards = reader.create_shards(records_per_shard=10)
    assert [(s.start, s.end) for s in shards] == [(0, 10), (10, 20), (20, 25)]
    assert list(reader.read_records(shards[2])) == [b"r20", b"r21", b"r22", b"r23", b"r24"]


def test_csv_reader_shards_and_header(tmp_path):
    path = str(tmp_path / "d.csv")
    path2 = str(tmp_path / "e.csv")
    open(path, "w").write("h1,h2\n1,a\n2,b\n3,c\n")
    open(path2, "w").write("h1,h2\n4,d\n")
    reader = CSVDataReader(str(tmp_path), skip_header=True)
    shards = sorted(reader.create_shards(2), key=lambda s: (s.name, s.start))
    assert [(s.start, s.end) for s in shards] == [(0, 2), (2, 3), (0, 1)]
    assert list(reader.read_records(Shard(path, 1, 3))) == [b"2,b", b"3,c"]


def test_format_sniffing(tmp_path):
    rio = str(tmp_path / "a.data")
    write_records(rio, [b"x"])
    csv = str(tmp_path / "b.data")
    open(csv, "w").write("1,2\n")
    assert isinstance(create_data_reader(rio), RecordIODataReader)
    assert isinstance(create_data_reader(csv), CSVDataReader)


def test_criteo_codec_roundtrip():
    rec = codecs.encode_criteo_example(1, list(range(13)), list(range(26)))
    batch = codecs.criteo_feed([rec, rec])
    assert batch["dense"].shape == (2, 13)
    assert batch["cat"].shape == (2, 26)
    np.testing.assert_array_equal(batch["labels"], [1, 1])
    np.testing.assert_array_equal(batch["cat"][0], np.arange(26))


def test_packed_records_sequence_semantics():
    from elasticdl_tpu.data.packed import PackedRecords, as_packed

    records = [b"alpha", b"", b"x" * 100, b"tail"]
    packed = as_packed(records)
    assert len(packed) == 4
    assert list(packed) == records
    assert packed[2] == records[2]
    assert packed[-1] == b"tail"
    view = packed[1:3]
    assert isinstance(view, PackedRecords)
    assert list(view) == records[1:3]
    assert view.tobytes() == b"".join(records[1:3])
    assert as_packed(packed) is packed
    with pytest.raises(ValueError):
        packed[::2]


def test_criteo_native_decode_matches_python():
    """The C++ decoder and the Python loop (the format's source of truth)
    must agree bit-for-bit — including blanks, missing trailing fields,
    negatives, decimals, and full-range hex ids."""
    from elasticdl_tpu.data.packed import as_packed
    from elasticdl_tpu.ps.host_store import native_lib_available

    if not native_lib_available():
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(3)
    records = [
        codecs.encode_criteo_example(
            int(rng.integers(0, 2)),
            [None if rng.random() < 0.2 else int(rng.integers(-50, 1000))
             for _ in range(13)],
            [int(rng.integers(0, 1 << 32)) for _ in range(26)],
        )
        for _ in range(256)
    ]
    records.append(b"1")                      # label only
    records.append(b"0\t\t\t")                # blank dense fields
    records.append(b"1\t3.5\t-2.25\t1e2")     # decimals + exponent
    records.append(b"0" + b"\t7" * 13 + b"\tdeadBEEF")  # mixed-case hex

    def py_feed(recs):
        n = len(recs)
        dense = np.zeros((n, 13), np.float32)
        cat = np.zeros((n, 26), np.int32)
        labels = np.zeros((n,), np.int32)
        for i, rec in enumerate(recs):
            parts = rec.decode().split("\t")
            labels[i] = int(parts[0])
            for j, v in enumerate(parts[1:14]):
                dense[i, j] = float(v) if v else 0.0
            for j, v in enumerate(parts[14:]):
                cat[i, j] = np.int32(np.uint32(int(v, 16))) if v else 0
        return {"dense": dense, "cat": cat, "labels": labels}

    ref = py_feed(records)
    for form in (records, as_packed(records)):
        out = codecs.criteo_feed(form)
        for key in ref:
            np.testing.assert_array_equal(ref[key], out[key], err_msg=key)


def test_criteo_native_decode_rejects_malformed():
    from elasticdl_tpu.ps.host_store import native_lib_available

    if not native_lib_available():
        pytest.skip("native lib unavailable")
    with pytest.raises(ValueError, match="record 1"):
        codecs.criteo_feed([b"1\t2", b"not-a-label\t2"])
    with pytest.raises(ValueError):  # non-hex categorical
        codecs.criteo_feed([b"1" + b"\t1" * 13 + b"\tzzzz"])


def test_recordio_packed_read_and_crc(tmp_path):
    path = str(tmp_path / "data.rio")
    records = [b"hello", b"", b"x" * 10_000, bytes(range(256))]
    write_records(path, records)
    reader = RecordIOReader(path)
    assert list(reader.read_range_packed(0, 4)) == records
    assert list(reader.read_range_packed(1, 3)) == records[1:3]
    assert list(reader.read_range_packed(3, 99)) == records[3:]
    assert len(reader.read_range_packed(2, 2)) == 0
    raw = bytearray(open(path, "rb").read())
    raw[-3] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    with pytest.raises(IOError):
        RecordIOReader(path).read_range_packed(0, 4)


def test_reader_packed_matches_iter(tmp_path):
    path = str(tmp_path / "d.rio")
    write_records(path, [b"r%d" % i for i in range(25)])
    reader = RecordIODataReader(path)
    shard = Shard(path, 10, 20)
    assert list(reader.read_records_packed(shard)) == list(
        reader.read_records(shard)
    )


def test_prefetch_order_and_errors():
    from elasticdl_tpu.data.prefetch import prefetch

    assert list(prefetch(iter(range(100)), depth=3)) == list(range(100))
    assert list(prefetch(iter(range(5)), depth=0)) == list(range(5))

    def boom():
        yield 1
        yield 2
        raise RuntimeError("decode failed")

    it = prefetch(boom(), depth=2)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(RuntimeError, match="decode failed"):
        next(it)


def test_criteo_feed_pre_matches_device_transforms():
    """The pipeline-preprocessed feed must equal the on-device transforms:
    cat buckets bit-for-bit (models/tabular.py hash), dense within one f16
    ulp of log1p, labels exact — for BOTH the C++ decoder and the numpy
    fallback."""
    from elasticdl_tpu.models.tabular import fuse_feature_ids_np
    from elasticdl_tpu.ps.host_store import native_lib_available

    rng = np.random.default_rng(5)
    records = [
        codecs.encode_criteo_example(
            int(rng.integers(0, 2)),
            [None if rng.random() < 0.2 else int(rng.integers(0, 100000))
             for _ in range(13)],
            [int(rng.integers(0, 1 << 32)) for _ in range(26)],
        )
        for _ in range(512)
    ]
    buckets = 65536
    raw = codecs.criteo_feed(records)
    expect_ids = fuse_feature_ids_np(raw["cat"], buckets)
    offsets = np.arange(26, dtype=np.int64) * buckets
    expect_dense = np.log1p(np.maximum(raw["dense"], 0.0))

    def check(pre):
        np.testing.assert_array_equal(
            pre["cat"].astype(np.int64) + offsets, expect_ids
        )
        np.testing.assert_array_equal(pre["labels"], raw["labels"])
        assert pre["dense"].dtype == np.float16
        np.testing.assert_allclose(
            pre["dense"].astype(np.float32), expect_dense, rtol=1e-3
        )

    if native_lib_available():
        check(codecs.criteo_feed_pre(records, buckets=buckets))
        # Native f16 rounding must match numpy's cast bit-for-bit.
        np.testing.assert_array_equal(
            codecs.criteo_feed_pre(records, buckets=buckets)["dense"].view(
                np.uint16
            ),
            expect_dense.astype(np.float16).view(np.uint16),
        )

    # numpy fallback (force it by importing the fallback branch directly)
    h = raw["cat"].astype(np.uint32) * np.uint32(2654435761)
    h ^= h >> np.uint32(16)
    fallback = {
        "dense": expect_dense.astype(np.float16),
        "cat": (h % np.uint32(buckets)).astype(np.uint16),
        "labels": raw["labels"].astype(np.uint8),
    }
    check(fallback)


def test_deepfm_pipeline_preprocess_matches_device_path(devices):
    """Same records through pipeline_preprocess=True and =False specs give
    the same logits (up to the f16 wire rounding, far below bf16 compute
    noise)."""
    import jax

    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    rng = np.random.default_rng(9)
    records = [
        codecs.encode_criteo_example(
            int(rng.integers(0, 2)),
            [int(rng.integers(0, 1000)) for _ in range(13)],
            [int(rng.integers(0, 1 << 32)) for _ in range(26)],
        )
        for _ in range(64)
    ]
    mesh = create_mesh(devices[:4])
    outs = {}
    for pre in (False, True):
        spec = load_model_spec(
            "elasticdl_tpu.models",
            "deepfm.model_spec",
            buckets_per_feature=512,
            embedding_dim=4,
            hidden=(16,),
            compute_dtype="float32",
            host_tier=False,
            pipeline_preprocess=pre,
        )
        batch = spec.feed(records)
        assert batch["cat"].dtype == (np.uint16 if pre else np.int32)
        trainer = Trainer(spec, JobConfig(), mesh)
        state = trainer.init_state(jax.random.key(0))
        outs[pre] = np.asarray(
            trainer.run_predict_step(state, batch)
        )
    np.testing.assert_allclose(outs[True], outs[False], rtol=2e-3, atol=2e-3)


def test_census_native_decode_matches_layers():
    """The C++ census decoder must equal the preprocessing-layer pipeline
    (ToNumber + Hashing crc32) bit-for-bit, including blanks, whitespace,
    decimals, and invalid numerics."""
    from elasticdl_tpu.preprocessing import Hashing, ToNumber
    from elasticdl_tpu.ps.host_store import native_lib_available

    if not native_lib_available():
        pytest.skip("native lib unavailable")
    records = [
        codecs.encode_census_example(0, [39, 13, 0, 0, 40], ["private"] * 9),
        codecs.encode_census_example(1, [17.5, 1, 5000, 0, 12.25], ["a b", ""] + ["x"] * 7),
        b"1, 39 ,13,,40,junk, gov,hs,married,tech,husband,white,male,us,a".replace(b"junk", b"oops"),
        b"0,1e2,2.5,-3,0.0,4,w1,w2,w3,w4,w5,w6,w7,w8,w9",
    ]

    def layer_feed(recs):
        to_number = ToNumber(out_dtype="float32", default=0.0)
        hashing = Hashing(1 << 31)
        n = len(recs)
        dense_raw = np.empty((n, 5), object)
        cat_raw = np.empty((n, 9), object)
        labels = np.zeros((n,), np.int32)
        for i, rec in enumerate(recs):
            parts = rec.decode().split(",")
            labels[i] = int(parts[0])
            dense_raw[i] = parts[1:6]
            cat_raw[i] = [v.strip() for v in parts[6:]]
        return {
            "dense": to_number(dense_raw),
            "cat": hashing(cat_raw).astype(np.int32),
            "labels": labels,
        }

    ref = layer_feed(records)
    out = codecs.census_feed(records)
    for key in ref:
        np.testing.assert_array_equal(ref[key], out[key], err_msg=key)


def test_census_codec_roundtrip():
    rec = codecs.encode_census_example(0, [39, 13, 0, 0, 40], ["private"] * 9)
    batch = codecs.census_feed([rec])
    assert batch["dense"].shape == (1, 5)
    assert batch["cat"].shape == (1, 9)
    assert (batch["cat"] >= 0).all()


@pytest.mark.parametrize(
    "family,model_def,n",
    [
        ("mnist", "mnist.model_spec", 64),
        ("cifar10", "cifar10_resnet.model_spec", 32),
        ("criteo", "deepfm.model_spec", 64),
        ("census", "wide_deep.model_spec", 64),
    ],
)
def test_synthetic_to_train_step(tmp_path, devices, family, model_def, n):
    """File on disk -> reader shard -> feed -> one mesh train step."""
    import jax

    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    path = str(tmp_path / f"{family}.data")
    synthetic.generate(family, path, n)
    reader = create_data_reader(path)
    shards = reader.create_shards(n)
    assert sum(s.size for s in shards) == n

    tiny = {
        "deepfm.model_spec": dict(buckets_per_feature=64, hidden=(16,)),
        "wide_deep.model_spec": dict(buckets=32, hidden=(16,)),
        "cifar10_resnet.model_spec": dict(depth=14, width=8),
    }.get(model_def, {})
    spec = load_model_spec(
        "elasticdl_tpu.models", model_def, compute_dtype="float32", **tiny
    )
    batch = spec.feed(list(reader.read_records(shards[0])))
    trainer = Trainer(spec, JobConfig(), create_mesh(devices))
    state = trainer.init_state(jax.random.key(0))
    state, metrics = trainer.train_step(state, trainer.shard_batch(batch))
    assert np.isfinite(float(metrics["loss"]))


def test_csv_packed_matches_iter(tmp_path):
    path = str(tmp_path / "d.csv")
    open(path, "wb").write(b"h1,h2\n1,a\r\n2,b\n3,c\n4,d")  # mixed EOLs, no final NL
    reader = CSVDataReader(path, skip_header=True)
    for shard in (Shard(path, 0, 4), Shard(path, 1, 3), Shard(path, 2, 99)):
        assert list(reader.read_records_packed(shard)) == list(
            reader.read_records(shard)
        )


def test_prefetch_cancellation_releases_producer():
    """An abandoned consumer must cancel the producer thread (pre-r4-review
    it parked on the bounded queue forever, pinning decoded batches)."""
    import threading
    import time

    from elasticdl_tpu.data.prefetch import prefetch

    produced = []

    def gen():
        for i in range(1000):
            produced.append(i)
            yield i

    before = threading.active_count()
    it = prefetch(gen(), depth=2)
    assert next(it) == 0
    it.close()  # abandon mid-iteration -> cancel event fires
    deadline = time.time() + 5.0
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before, "producer thread leaked"
    assert len(produced) < 1000  # producer stopped early, not drained


def test_prefetch_abandoned_before_first_pull_starts_no_thread():
    """A generator abandoned before its first next() never runs its body, so
    its finally can't cancel anything — the producer must therefore start
    lazily on the first pull (ADVICE r4 #1), or it would spin forever."""
    import gc
    import threading

    from elasticdl_tpu.data.prefetch import prefetch

    produced = []

    def gen():
        for i in range(1000):
            produced.append(i)
            yield i

    before = threading.active_count()
    it = prefetch(gen(), depth=2)
    del it  # abandoned: no next() ever happens
    gc.collect()
    assert threading.active_count() <= before, "producer started eagerly"
    assert produced == []


# ---------------- parallel ingest (r9, data/ingest_pool.py) ----------------


def test_plan_chunks_alignment_and_cover():
    from elasticdl_tpu.data.ingest_pool import plan_chunks

    # Interior boundaries minibatch-aligned, range covered exactly, tail on
    # the last chunk, chunk count bounded by threads.
    for start, end, mb, threads in (
        (0, 100, 16, 4), (32, 131, 16, 4), (0, 5, 16, 4), (0, 64, 16, 3),
        (7, 7, 16, 4), (0, 1000, 1, 8), (0, 33, 16, 2),
    ):
        chunks = plan_chunks(start, end, mb, threads)
        assert chunks[0][0] == start and chunks[-1][1] == max(start, end)
        for (a, b), (c, d) in zip(chunks, chunks[1:]):
            assert b == c, chunks
            assert (b - start) % mb == 0, chunks  # interior cut is aligned
        assert len(chunks) <= max(1, threads)
        # only the last chunk may hold a non-multiple of mb
        for a, b in chunks[:-1]:
            assert (b - a) % mb == 0
    # nothing to split: single chunk back
    assert plan_chunks(0, 31, 16, 4) == [(0, 31)]  # 1 full mb + tail
    assert plan_chunks(0, 100, 16, 1) == [(0, 100)]


def test_ingest_pool_map_ordered_preserves_order_and_raises():
    from elasticdl_tpu.data.ingest_pool import IngestPool

    pool = IngestPool(4)
    assert pool.parallel and pool.threads == 4
    try:
        out = pool.map_ordered(lambda x: x * x, list(range(37)))
        assert out == [x * x for x in range(37)]

        def boom(x):
            if x == 5:
                raise ValueError("chunk failure")
            return x

        with pytest.raises(ValueError, match="chunk failure"):
            pool.map_ordered(boom, list(range(8)))
    finally:
        pool.shutdown()
    # serial degradation: no pool at all, same results
    serial = IngestPool(1)
    assert not serial.parallel
    assert serial.map_ordered(lambda x: -x, [3, 1, 2]) == [-3, -1, -2]


def test_parallel_chunk_decode_bit_identical(tmp_path):
    """The r9 contract: chunked read+decode reassembled in chunk order is
    byte-for-byte the serial path's output — record order preserved across
    an mb-unaligned shard with a ragged tail."""
    from elasticdl_tpu.data.ingest_pool import IngestPool, plan_chunks

    path = str(tmp_path / "c.rio")
    n, mb = 1000, 64  # 15 full minibatches + 40-record tail
    synthetic.synthetic_criteo(path, n, seed=3, container="recordio")
    reader = create_data_reader(path)
    assert reader.thread_safe_ranges
    shard = Shard(path, 0, n)

    serial = codecs.criteo_feed_pre(reader.read_records_packed(shard), 4096)

    pool = IngestPool(4)
    try:
        chunks = plan_chunks(shard.start, shard.end, mb, pool.threads)
        assert len(chunks) == 4
        parts = pool.map_ordered(
            lambda span: codecs.criteo_feed_pre(
                reader.read_records_packed(Shard(path, span[0], span[1])),
                4096,
            ),
            chunks,
        )
    finally:
        pool.shutdown()
    merged = {
        k: np.concatenate([p[k] for p in parts], axis=0) for k in serial
    }
    assert set(merged) == set(serial)
    for k in serial:
        assert merged[k].dtype == serial[k].dtype
        np.testing.assert_array_equal(merged[k], serial[k])


def test_recordio_offsets_cache_shared_across_readers(tmp_path):
    """The process-level (path, mtime, size) offsets cache: a second reader
    instance of the same unchanged file reuses the first's index (no
    re-scan), while a rewritten file gets a fresh scan."""
    from elasticdl_tpu.data import recordio as rio

    path = str(tmp_path / "cache.rio")
    write_records(path, [b"a" * 10, b"b" * 20, b"c" * 5])
    r1 = RecordIOReader(path)
    idx1 = r1.index()
    r2 = RecordIOReader(path)
    assert r2.index() is idx1  # shared list object: served from the cache

    # Rewrite with different content: the key (mtime_ns, size) changes, so
    # the stale index must not be reused.
    import os as _os
    write_records(path, [b"x" * 7, b"y" * 300])
    _os.utime(path, ns=(1, 1))  # force a distinct mtime even on coarse fs
    r3 = RecordIOReader(path)
    idx3 = r3.index()
    assert idx3 is not idx1 and len(idx3) == 2
    assert list(r3.read_range(0, 2)) == [b"x" * 7, b"y" * 300]
    # bounded: the cache never grows past its cap
    assert len(rio._INDEX_CACHE) <= rio._INDEX_CACHE_MAX


def test_prefetch_thread_name_attributes_task():
    """The producer thread carries the caller's name (prefetch:<task_id>)
    so thread dumps attribute ingest threads."""
    import threading
    from elasticdl_tpu.data.prefetch import prefetch

    names = []

    def gen():
        names.append(threading.current_thread().name)
        yield 1

    assert list(prefetch(gen(), 2, name="prefetch:42")) == [1]
    assert names == ["prefetch:42"]
