"""Data layer: recordio format round-trip, shard range addressing, codec
round-trips, and end-to-end synthetic-file -> feed -> train-step for each
model family (the reference's data-reader unit tests, SURVEY.md §4)."""

import numpy as np
import pytest

from elasticdl_tpu.data import codecs, synthetic
from elasticdl_tpu.data.reader import (
    CSVDataReader,
    RecordIODataReader,
    Shard,
    create_data_reader,
)
from elasticdl_tpu.data.recordio import RecordIOReader, write_records


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "data.rio")
    records = [b"hello", b"", b"x" * 10_000, bytes(range(256))]
    assert write_records(path, records) == 4
    reader = RecordIOReader(path)
    assert len(reader) == 4
    assert list(reader.read_range(0, 4)) == records
    assert list(reader.read_range(1, 3)) == records[1:3]
    assert list(reader.read_range(3, 99)) == records[3:]


def test_recordio_crc_detects_corruption(tmp_path):
    path = str(tmp_path / "data.rio")
    write_records(path, [b"payload-one", b"payload-two"])
    raw = bytearray(open(path, "rb").read())
    raw[-3] ^= 0xFF  # flip a byte inside the last payload
    open(path, "wb").write(bytes(raw))
    reader = RecordIOReader(path)
    with pytest.raises(IOError):
        list(reader.read_range(0, 2))


def test_recordio_reader_shards(tmp_path):
    path = str(tmp_path / "d.rio")
    write_records(path, [b"r%d" % i for i in range(25)])
    reader = RecordIODataReader(path)
    shards = reader.create_shards(records_per_shard=10)
    assert [(s.start, s.end) for s in shards] == [(0, 10), (10, 20), (20, 25)]
    assert list(reader.read_records(shards[2])) == [b"r20", b"r21", b"r22", b"r23", b"r24"]


def test_csv_reader_shards_and_header(tmp_path):
    path = str(tmp_path / "d.csv")
    path2 = str(tmp_path / "e.csv")
    open(path, "w").write("h1,h2\n1,a\n2,b\n3,c\n")
    open(path2, "w").write("h1,h2\n4,d\n")
    reader = CSVDataReader(str(tmp_path), skip_header=True)
    shards = sorted(reader.create_shards(2), key=lambda s: (s.name, s.start))
    assert [(s.start, s.end) for s in shards] == [(0, 2), (2, 3), (0, 1)]
    assert list(reader.read_records(Shard(path, 1, 3))) == [b"2,b", b"3,c"]


def test_format_sniffing(tmp_path):
    rio = str(tmp_path / "a.data")
    write_records(rio, [b"x"])
    csv = str(tmp_path / "b.data")
    open(csv, "w").write("1,2\n")
    assert isinstance(create_data_reader(rio), RecordIODataReader)
    assert isinstance(create_data_reader(csv), CSVDataReader)


def test_criteo_codec_roundtrip():
    rec = codecs.encode_criteo_example(1, list(range(13)), list(range(26)))
    batch = codecs.criteo_feed([rec, rec])
    assert batch["dense"].shape == (2, 13)
    assert batch["cat"].shape == (2, 26)
    np.testing.assert_array_equal(batch["labels"], [1, 1])
    np.testing.assert_array_equal(batch["cat"][0], np.arange(26))


def test_census_codec_roundtrip():
    rec = codecs.encode_census_example(0, [39, 13, 0, 0, 40], ["private"] * 9)
    batch = codecs.census_feed([rec])
    assert batch["dense"].shape == (1, 5)
    assert batch["cat"].shape == (1, 9)
    assert (batch["cat"] >= 0).all()


@pytest.mark.parametrize(
    "family,model_def,n",
    [
        ("mnist", "mnist.model_spec", 64),
        ("cifar10", "cifar10_resnet.model_spec", 32),
        ("criteo", "deepfm.model_spec", 64),
        ("census", "wide_deep.model_spec", 64),
    ],
)
def test_synthetic_to_train_step(tmp_path, devices, family, model_def, n):
    """File on disk -> reader shard -> feed -> one mesh train step."""
    import jax

    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    path = str(tmp_path / f"{family}.data")
    synthetic.generate(family, path, n)
    reader = create_data_reader(path)
    shards = reader.create_shards(n)
    assert sum(s.size for s in shards) == n

    tiny = {
        "deepfm.model_spec": dict(buckets_per_feature=64, hidden=(16,)),
        "wide_deep.model_spec": dict(buckets=32, hidden=(16,)),
        "cifar10_resnet.model_spec": dict(depth=14, width=8),
    }.get(model_def, {})
    spec = load_model_spec(
        "elasticdl_tpu.models", model_def, compute_dtype="float32", **tiny
    )
    batch = spec.feed(list(reader.read_records(shards[0])))
    trainer = Trainer(spec, JobConfig(), create_mesh(devices))
    state = trainer.init_state(jax.random.key(0))
    state, metrics = trainer.train_step(state, trainer.shard_batch(batch))
    assert np.isfinite(float(metrics["loss"]))
