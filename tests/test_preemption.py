"""Graceful preemption (SIGTERM -> snapshot -> RESTART exit -> resume).

k8s preemption delivers SIGTERM with a grace window before SIGKILL; the
worker's handler (worker.main._install_preemption_handler) snapshots the
live state when safe and exits RESTART_EXIT_CODE so the relaunch is
budget-free and resumes from the preemption step, not the last periodic
checkpoint.  This drives a REAL worker process: periodic checkpoints are
disabled, so any restorable step can only have come from the preemption
snapshot.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.data.synthetic import generate
from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(config, log_path):
    env = dict(os.environ)
    env.update(config.to_env())
    env["ELASTICDL_WORKER_ID"] = "preempt-w0"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    with open(log_path, "w") as log:  # the child keeps its own fd
        return subprocess.Popen(
            [sys.executable, "-m", "elasticdl_tpu.worker.main"],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=_REPO,
        )


@pytest.mark.slow
def test_sigterm_snapshots_and_resume(tmp_path):
    from elasticdl_tpu.worker.worker import RESTART_EXIT_CODE

    path = str(tmp_path / "train.rio")
    generate("mnist", path, 256)
    shards = create_data_reader(path).create_shards(16)
    dispatcher = TaskDispatcher(shards, num_epochs=50)
    servicer = MasterServicer(dispatcher)
    server = MasterServer(servicer, port=0).start()
    procs = []
    try:
        config = JobConfig(
            model_def="mnist.model_spec",
            model_params="compute_dtype=float32",
            training_data=path,
            minibatch_size=16,
            num_epochs=50,
            master_addr=server.address,
            checkpoint_dir=str(tmp_path / "ckpt"),
            checkpoint_steps=0,  # snapshot can ONLY come from preemption
        )
        proc = _spawn(config, tmp_path / "w.log.0")
        procs.append(proc)
        deadline = time.time() + 240
        while time.time() < deadline:
            if servicer.JobStatus({})["done"] >= 2:
                break
            time.sleep(0.1)
        else:
            pytest.fail("worker never made progress")

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        assert rc == RESTART_EXIT_CODE

        from elasticdl_tpu.common.checkpoint import CheckpointManager

        ckpt = CheckpointManager(config.checkpoint_dir)
        snap_step = ckpt.latest_step()
        assert snap_step is not None and snap_step > 0

        # Relaunch resumes FROM THE PREEMPTION SNAPSHOT and keeps training.
        done_before = servicer.JobStatus({})["done"]
        proc2 = _spawn(config, tmp_path / "w.log.1")
        procs.append(proc2)
        deadline = time.time() + 240
        while time.time() < deadline:
            if servicer.JobStatus({})["done"] > done_before:
                break
            time.sleep(0.2)
        else:
            pytest.fail("relaunch never resumed training")
        proc2.kill()
        log = (tmp_path / "w.log.1").read_text()
        assert f"joined from checkpoint step {snap_step}" in log
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
