"""AllReduce trainer: parity of the mesh-psum step with a single-device step,
and convergence on a learnable toy problem — the TPU-native analogue of the
reference's AllReduceTrainer unit tests (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.parallel.trainer import Trainer


def _batch(rng, n=64):
    images = jax.random.normal(rng, (n, 28, 28, 1), jnp.float32)
    labels = jax.random.randint(jax.random.fold_in(rng, 1), (n,), 0, 10)
    return {"images": images, "labels": labels}


@pytest.fixture(scope="module")
def spec():
    return load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )


def test_step_runs_on_8_device_mesh(spec, devices):
    mesh = create_mesh(devices)
    trainer = Trainer(spec, JobConfig(), mesh)
    state = trainer.init_state(jax.random.key(0))
    batch = trainer.shard_batch(_batch(jax.random.key(1)))
    new_state, metrics = trainer.train_step(state, batch)
    assert int(new_state.step) == 1
    assert np.isfinite(float(metrics["loss"]))
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_psum_step_matches_single_device(spec, devices):
    """Same global batch, mesh of 8 vs mesh of 1 => identical updates."""
    batch = _batch(jax.random.key(2), n=32)

    results = []
    for n_dev in (1, 8):
        mesh = create_mesh(devices, num_devices=n_dev)
        trainer = Trainer(spec, JobConfig(), mesh)
        state = trainer.init_state(jax.random.key(0))
        sharded = trainer.shard_batch(batch)
        state, metrics = trainer.train_step(state, sharded)
        results.append((jax.device_get(state.params), float(metrics["loss"])))

    p1, loss1 = results[0]
    p8, loss8 = results[1]
    assert abs(loss1 - loss8) < 1e-4
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p8)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_loss_decreases(spec, devices):
    mesh = create_mesh(devices)
    trainer = Trainer(spec, JobConfig(), mesh)
    state = trainer.init_state(jax.random.key(0))
    batch = trainer.shard_batch(_batch(jax.random.key(3), n=64))
    first = None
    for _ in range(10):
        state, metrics = trainer.train_step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first


def test_eval_step(spec, devices):
    mesh = create_mesh(devices)
    trainer = Trainer(spec, JobConfig(), mesh)
    state = trainer.init_state(jax.random.key(0))
    batch = trainer.shard_batch(_batch(jax.random.key(4)))
    metrics = trainer.eval_step(state, batch)
    assert set(metrics) >= {"accuracy", "loss"}


def test_masked_train_tail_matches_unpadded(spec, devices):
    """A wrap-padded training tail with ``__mask__`` must produce EXACTLY the
    update of the true partial batch: padded duplicates carry zero gradient
    (VERDICT r3 item 4 — eval got the mask in r3, training gets it here)."""
    real, padded_size = 10, 16
    b = _batch(jax.random.key(7), n=real)
    # Wrap-pad like worker._minibatches: records repeat cyclically.
    idx = np.arange(padded_size) % real
    padded = {k: np.asarray(v)[idx] for k, v in b.items()}
    padded["__mask__"] = (np.arange(padded_size) < real).astype(np.float32)

    mesh = create_mesh(devices[:1])
    trainer_m = Trainer(spec, JobConfig(), mesh)
    state = trainer_m.init_state(jax.random.key(0))
    host_state = jax.device_get(state)  # before the step donates its buffers
    masked_state, masked_metrics = trainer_m.train_step(
        state, trainer_m.shard_batch(padded)
    )

    trainer_t = Trainer(spec, JobConfig(), mesh)
    state_t = trainer_t.shard_state(host_state)
    truth_state, truth_metrics = trainer_t.train_step(
        state_t,
        trainer_t.shard_batch({k: np.asarray(v) for k, v in b.items()}),
    )

    assert abs(
        float(masked_metrics["loss"]) - float(truth_metrics["loss"])
    ) < 1e-6
    for a, t in zip(
        jax.tree.leaves(jax.device_get(masked_state.params)),
        jax.tree.leaves(jax.device_get(truth_state.params)),
    ):
        np.testing.assert_allclose(a, t, rtol=1e-5, atol=1e-6)


def test_masked_tail_differs_from_unmasked_padding(spec, devices):
    """Without the mask the duplicated examples double-count (the r3 bug);
    this pins that the mask actually changes the update."""
    real, padded_size = 10, 16
    b = _batch(jax.random.key(8), n=real)
    idx = np.arange(padded_size) % real
    padded = {k: np.asarray(v)[idx] for k, v in b.items()}
    mesh = create_mesh(devices[:1])
    trainer = Trainer(spec, JobConfig(), mesh)
    state = trainer.init_state(jax.random.key(0))
    host_state = jax.device_get(state)
    unmasked_state, _ = trainer.train_step(state, trainer.shard_batch(padded))

    masked = dict(padded)
    masked["__mask__"] = (np.arange(padded_size) < real).astype(np.float32)
    trainer2 = Trainer(spec, JobConfig(), mesh)
    state2 = trainer2.shard_state(host_state)
    masked_state, _ = trainer2.train_step(state2, trainer2.shard_batch(masked))
    diffs = [
        np.max(np.abs(np.asarray(a) - np.asarray(t)))
        for a, t in zip(
            jax.tree.leaves(jax.device_get(masked_state.params)),
            jax.tree.leaves(jax.device_get(unmasked_state.params)),
        )
    ]
    assert max(diffs) > 1e-7


def test_train_scan_matches_step_loop(spec, devices):
    """The fused lax.scan task (one dispatch, T steps) must produce the
    same params and per-step losses as T individual train_step calls."""
    T, mb = 3, 16
    rng = np.random.default_rng(4)
    stacked_host = {
        "images": rng.standard_normal((T, mb, 28, 28, 1)).astype(np.float32),
        "labels": rng.integers(0, 10, (T, mb)).astype(np.int32),
    }
    mesh = create_mesh(devices)

    trainer_a = Trainer(spec, JobConfig(), mesh)
    state = trainer_a.init_state(jax.random.key(0))
    host_state = jax.device_get(state)
    loop_losses = []
    for t in range(T):
        batch = {k: v[t] for k, v in stacked_host.items()}
        state, m = trainer_a.train_step(state, trainer_a.shard_batch(batch))
        loop_losses.append(float(m["loss"]))
    loop_params = jax.device_get(state.params)

    trainer_b = Trainer(spec, JobConfig(), mesh)
    state_b = trainer_b.shard_state(host_state)
    state_b, metrics = trainer_b.train_scan(
        state_b, trainer_b.shard_stacked_batch(stacked_host)
    )
    scan_losses = [float(x) for x in np.asarray(metrics["loss"])]
    np.testing.assert_allclose(scan_losses, loop_losses, rtol=1e-5, atol=1e-6)
    assert int(state_b.step) == T
    for a, b in zip(
        jax.tree.leaves(loop_params),
        jax.tree.leaves(jax.device_get(state_b.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_sharded_optimizer_matches_replicated(spec, devices):
    """ZeRO-sharded update parity: same seed, same batches, 3 steps on a
    4-way mesh — sharded params track replicated to float32 last-ulp
    (psum vs psum_scatter reduce in different ring orders, so exact bit
    equality is not guaranteed; the RESIZE path, which is pure data
    movement, is asserted bit-exact in test_elastic)."""
    mesh = create_mesh(devices, num_devices=4)
    tr = Trainer(spec, JobConfig(), mesh)
    state_r = tr.init_state(jax.random.key(0))
    ts = Trainer(spec, JobConfig(optimizer_sharding="sharded"), mesh)
    state_s = ts.init_state(jax.random.key(0))

    # The memory claim itself: each device holds ~1/4 of the param-shaped
    # optimizer slots instead of a full copy.
    rep = max(tr.opt_state_bytes_per_device(state_r).values())
    sh = max(ts.opt_state_bytes_per_device(state_s).values())
    assert sh <= rep / 4 * 1.05 + 1024  # /dp plus padding slack

    for i in range(3):
        b = _batch(jax.random.key(20 + i))
        state_r, m_r = tr.train_step(state_r, tr.shard_batch(b))
        state_s, m_s = ts.train_step(state_s, ts.shard_batch(b))
    assert abs(float(m_r["loss"]) - float(m_s["loss"])) < 1e-6
    for a, b in zip(
        jax.tree.leaves(jax.device_get(state_r.params)),
        jax.tree.leaves(jax.device_get(state_s.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=2e-6, atol=1e-7)


def test_sharded_train_scan_matches_step_loop(spec, devices):
    """The fused lax.scan task must carry the FLAT sharded optimizer state
    through its scan body identically to per-step dispatch."""
    T, mb = 3, 16
    rng = np.random.default_rng(9)
    stacked = {
        "images": rng.standard_normal((T, mb, 28, 28, 1)).astype(np.float32),
        "labels": rng.integers(0, 10, (T, mb)).astype(np.int32),
    }
    mesh = create_mesh(devices, num_devices=4)
    cfg = JobConfig(optimizer_sharding="sharded")
    t1 = Trainer(spec, cfg, mesh)
    state = t1.init_state(jax.random.key(0))
    host = t1.host_state(state)
    losses = []
    for t in range(T):
        b = {k: v[t] for k, v in stacked.items()}
        state, m = t1.train_step(state, t1.shard_batch(b))
        losses.append(float(m["loss"]))

    t2 = Trainer(spec, cfg, mesh)
    state2 = t2.shard_state(host)
    state2, metrics = t2.train_scan(state2, t2.shard_stacked_batch(stacked))
    np.testing.assert_allclose(
        [float(x) for x in np.asarray(metrics["loss"])], losses,
        rtol=1e-5, atol=1e-6,
    )
    for a, b in zip(
        jax.tree.leaves(jax.device_get(state.params)),
        jax.tree.leaves(jax.device_get(state2.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_auto_mode_thresholds_on_state_size(spec, devices):
    """auto = sharded iff the replicated dense optimizer state exceeds the
    threshold; dp=1 meshes never shard (nothing to cut)."""
    mesh = create_mesh(devices, num_devices=4)
    big = Trainer(
        spec,
        JobConfig(optimizer_sharding="auto", optimizer_sharding_auto_mb=1e-3),
        mesh,
    )
    big.init_state(jax.random.key(0))
    assert big._opt_plan is not None
    small = Trainer(
        spec,
        JobConfig(optimizer_sharding="auto", optimizer_sharding_auto_mb=1e6),
        mesh,
    )
    small.init_state(jax.random.key(0))
    assert small._opt_plan is None
    one = Trainer(
        spec, JobConfig(optimizer_sharding="sharded"),
        create_mesh(devices, num_devices=1),
    )
    one.init_state(jax.random.key(0))
    assert one._opt_plan is None


def test_donation_knob_off_keeps_input_state_alive(spec, devices):
    """--donate_train_state=false: the jitted step must NOT consume its
    input buffers (the debugging trade documented in common/config.py)."""
    mesh = create_mesh(devices, num_devices=2)
    t = Trainer(spec, JobConfig(donate_train_state=False), mesh)
    state = t.init_state(jax.random.key(0))
    new_state, _ = t.train_step(state, t.shard_batch(_batch(jax.random.key(3))))
    assert not any(
        leaf.is_deleted() for leaf in jax.tree.leaves(state)
    )
    assert int(new_state.step) == 1
