"""Serving fleet (r19): the multi-replica front.

Covers the four pieces that make one serving address into a fleet:

- the autoscale CONTROL LAW (windowed online-p99 + shed signals, deadband
  + streak + cooldown hysteresis) against synthetic scrapes — pure logic,
  no servers;
- the p2c client (power-of-two-choices over shared inflight counts,
  suspect marking, retry-on-another-replica via the shared backoff
  helper) against stub replicas;
- the tier-1 fleet smoke: REAL ServingServer replicas in-process behind a
  ServingFleetController, scale-up under a live ramp (the tight SLO is
  genuinely blown by real latencies) then scale-down when idle, with p2c
  traffic spread and bucketed-compile jitsan budgets holding fleet-wide;
- controller-restart adoption: a second controller over the same r18
  reattach registry re-owns the still-serving fleet without spawning a
  single duplicate replica.
"""

import random
import threading
import time

import grpc
import numpy as np
import pytest

from elasticdl_tpu.common import gauge as gaugelib
from elasticdl_tpu.common import jitsan
from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.master.pod_manager import FakePodBackend
from elasticdl_tpu.serving.client import FleetServingClient
from elasticdl_tpu.serving.fleet import (
    AutoscaleConfig,
    InProcessServingBackend,
    ServingFleetController,
    _delta_quantile,
)

# --------------------------------------------------- control-law units


def test_delta_quantile_windows_between_scrapes():
    inf = float("inf")
    cur = {10.0: 100.0, 40.0: 200.0, inf: 200.0}
    # No previous scrape: the quantile of the whole cumulative history.
    assert _delta_quantile(cur, None, 0.5) == pytest.approx(10.0)
    # Window = the 100 observations that landed in (10, 40] since prev.
    prev = {10.0: 100.0, 40.0: 100.0, inf: 100.0}
    q = _delta_quantile(cur, prev, 0.99)
    assert 10.0 < q <= 40.0
    # Empty window reads as NO SIGNAL, never as "p99 = 0".
    assert _delta_quantile(cur, cur, 0.99) is None
    assert _delta_quantile({}, None, 0.99) is None


#: Synthetic-histogram grid: an edge inside each regime of the law under
#: target 50 ms — low (p99 ~9.9 -> slo 0.2), deadband (p99 ~39.7 -> slo
#: 0.79, between down_slo 0.6 and up_slo 1.0), high (p99 ~99.4 -> slo 2).
_EDGES = (10.0, 40.0, 100.0, float("inf"))


class _SyntheticSignal:
    """Injectable scrape_fn: per-address CUMULATIVE families, 100 new
    online-lane observations per scrape in the current mode's bucket —
    so the controller's windowed differencing sees a steady rate."""

    def __init__(self):
        self.mode = "low"  # low | mid | high
        self.shed_online = 0.0
        self.shed_bulk = 0.0
        self._cum = {}

    def __call__(self, addr):
        cum = self._cum.setdefault(addr, {e: 0.0 for e in _EDGES})
        fill_from = {"low": 10.0, "mid": 40.0, "high": 100.0}[self.mode]
        for e in _EDGES:
            if e >= fill_from:
                cum[e] += 100.0
        hist = [
            {"name": "edl_serving_request_ms_bucket",
             "labels": {"lane": "online",
                        "le": "+Inf" if e == float("inf") else str(e)},
             "value": c}
            for e, c in cum.items()
        ]
        sheds = [
            {"name": "edl_serving_shed_total",
             "labels": {"lane": "online"}, "value": self.shed_online},
            {"name": "edl_serving_shed_total",
             "labels": {"lane": "bulk"}, "value": self.shed_bulk},
        ]
        return {
            "edl_serving_request_ms": {
                "type": "histogram", "help": "", "samples": hist},
            "edl_serving_shed_total": {
                "type": "counter", "help": "", "samples": sheds},
        }


def _unit_controller(sig, **auto_overrides):
    auto = dict(
        min_replicas=1, max_replicas=3, poll_s=0.01, target_p99_ms=50.0,
        up_slo=1.0, down_slo=0.6, up_consecutive=2, down_consecutive=3,
        cooldown_polls=2,
    )
    auto.update(auto_overrides)
    return ServingFleetController(
        FakePodBackend(), JobConfig(job_name="fleet-unit"),
        autoscale=AutoscaleConfig(**auto),
        autoscale_enabled=False,  # polls driven deterministically
        gauges=gaugelib.Registry(),
        scrape_fn=sig,
    )


def test_autoscaler_hysteresis_converges_up_then_down():
    sig = _SyntheticSignal()
    ctl = _unit_controller(sig)
    ctl.start(1)
    try:
        # UP: pressure must persist up_consecutive polls before acting.
        sig.mode = "high"
        d = ctl.poll_once()
        assert d["action"] == "" and d["up_streak"] == 1
        assert d["slo"] == pytest.approx(1.988, abs=0.01)
        d = ctl.poll_once()
        assert d["action"] == "up" and d["desired"] == 2
        # Cooldown: the fleet's response to THIS action is measured before
        # the next one — pressured polls right after do not act.
        assert ctl.poll_once()["action"] == ""
        assert ctl.poll_once()["action"] == ""
        d = ctl.poll_once()
        assert d["action"] == "up" and d["desired"] == 3
        # At max: sustained pressure never overshoots.
        for _ in range(4):
            assert ctl.poll_once()["action"] == ""
        assert ctl.pods.desired() == 3

        # DEADBAND: a borderline signal resets BOTH streaks — the zone
        # that turns an open-loop ramp into convergence, not flapping.
        sig.mode = "mid"
        for _ in range(6):
            d = ctl.poll_once()
            assert (d["action"], d["up_streak"], d["down_streak"]) == ("", 0, 0)

        # DOWN: slower on purpose (down_consecutive > up_consecutive).
        sig.mode = "low"
        acts = [ctl.poll_once()["action"] for _ in range(3)]
        assert acts == ["", "", "down"] and ctl.pods.desired() == 2
        acts = [ctl.poll_once()["action"] for _ in range(5)]
        assert acts.count("down") == 1 and ctl.pods.desired() == 1
        # At min: sustained quiet never undershoots.
        for _ in range(4):
            assert ctl.poll_once()["action"] == ""
        assert ctl.pods.desired() == 1

        assert [(e["from"], e["to"]) for e in ctl.events()] == [
            (1, 2), (2, 3), (3, 2), (2, 1)
        ]
    finally:
        ctl.stop()


def test_autoscaler_shed_signals():
    """Online sheds are scale-up pressure even at low latency (the knee
    shows as shedding before it shows as p99); bulk sheds only VETO
    scale-down (expected under shed-bulk-first, not a capacity alarm)."""
    sig = _SyntheticSignal()
    ctl = _unit_controller(sig)
    ctl.start(1)
    try:
        sig.mode = "low"
        d = ctl.poll_once()  # first scrape = shed baseline
        assert d["shed_online"] == 0 and d["down_streak"] == 1
        sig.shed_online += 5
        d = ctl.poll_once()
        assert d["shed_online"] == 5
        assert d["up_streak"] == 1 and d["down_streak"] == 0
        sig.shed_bulk += 3
        d = ctl.poll_once()
        assert d["shed_total"] == 3 and d["shed_online"] == 0
        # Neither up (online is fine) nor down (the window saw sheds).
        assert d["up_streak"] == 0 and d["down_streak"] == 0
        d = ctl.poll_once()  # quiet window: down pressure resumes
        assert d["down_streak"] == 1
    finally:
        ctl.stop()


def test_scale_down_drains_before_delete_and_up_cancels_drain():
    """Graceful retirement: a scale-down victim leaves the membership
    IMMEDIATELY (clients stop picking it before the pod can vanish) but
    its pod is deleted only after drain_s — and pressure returning
    mid-drain folds the still-warm victim back in instead of spawning."""
    sig = _SyntheticSignal()
    t = [0.0]
    ctl = ServingFleetController(
        FakePodBackend(), JobConfig(job_name="fleet-drain"),
        autoscale=AutoscaleConfig(
            min_replicas=1, max_replicas=2, poll_s=0.01, target_p99_ms=50.0,
            up_consecutive=1, down_consecutive=1, cooldown_polls=0,
            drain_s=5.0,
        ),
        autoscale_enabled=False,
        gauges=gaugelib.Registry(),
        scrape_fn=sig,
        clock=lambda: t[0],
    )
    ctl.start(2)
    try:
        sig.mode = "low"
        d = ctl.poll_once()
        assert d["action"] == "down"
        assert len(ctl.replicas()) == 1 and ctl.pods.desired() == 2

        sig.mode = "high"
        d = ctl.poll_once()
        assert d["action"] == "up"
        # Un-drained, not respawned: same two pods, both in membership.
        assert len(ctl.replicas()) == 2 and ctl.pods.desired() == 2

        sig.mode = "low"
        d = ctl.poll_once()
        assert d["action"] == "down" and ctl.pods.desired() == 2
        t[0] = 6.0  # past the drain deadline
        ctl.poll_once()
        assert ctl.pods.desired() == 1 and len(ctl.replicas()) == 1

        assert [(e["from"], e["to"]) for e in ctl.events()] == [
            (2, 1), (1, 2), (2, 1)
        ]
    finally:
        ctl.stop()


# ------------------------------------------------------- p2c client


class _FakeRpcError(grpc.RpcError):
    def __init__(self, code):
        self._code = code

    def code(self):
        return self._code

    def details(self):
        return "stub failure"


class _StubReplica:
    def __init__(self, name, fail=None):
        self.name = name
        self.fail = fail
        self.calls = 0

    def predict(self, features, timeout_s=30.0, lane="online"):
        self.calls += 1
        if self.fail is not None:
            raise self.fail
        return {"outputs": [0.5], "model": "stub", "step": 0}

    def close(self):
        pass


def _stub_fleet(names, rng_seed=7):
    fc = FleetServingClient(list(names), rng=random.Random(rng_seed))
    with fc._lock:
        for c in fc._clients.values():
            c.close()
        fc._clients = {n: _StubReplica(n) for n in names}
    return fc


def test_fleet_client_p2c_spreads_and_retries_transient_elsewhere():
    fc = _stub_fleet(["a:1", "b:1"])
    for _ in range(40):
        assert fc.predict({"x": [1]})["model"] == "stub"
    a, b = fc._clients["a:1"], fc._clients["b:1"]
    assert a.calls > 0 and b.calls > 0  # p2c routed to both
    assert fc.inflight() == {"a:1": 0, "b:1": 0}  # counts balanced back out

    # One replica turns UNAVAILABLE (mid-retirement): the predict still
    # succeeds via a re-pick, and the failed replica sits out as suspect.
    a.fail = _FakeRpcError(grpc.StatusCode.UNAVAILABLE)
    a.calls = b.calls = 0
    for _ in range(10):
        assert fc.predict({"x": [1]})["model"] == "stub"
    assert b.calls >= 10
    assert fc._suspect_until.get("a:1", 0.0) > 0.0
    fc.close()


def test_fleet_client_non_transient_errors_surface_immediately():
    fc = _stub_fleet(["a:1"])
    stub = fc._clients["a:1"]
    stub.fail = _FakeRpcError(grpc.StatusCode.INVALID_ARGUMENT)
    with pytest.raises(grpc.RpcError):
        fc.predict({"x": [1]})
    assert stub.calls == 1  # no retry: a schema error is the caller's bug
    assert fc._suspect_until.get("a:1", 0.0) == 0.0  # and not health signal
    fc.close()


def test_fleet_client_membership_refresh():
    fc = FleetServingClient(["x:1", "y:1"])
    assert fc.addresses() == ["x:1", "y:1"]
    fc.set_replicas(["y:1", "z:1"])  # x retired, z joined
    assert fc.addresses() == ["y:1", "z:1"]
    fc.close()
    assert fc.addresses() == []


def test_fleet_client_lingers_retired_channel_until_inflight_drains():
    """A removed replica's channel must NOT close under a request still
    riding it (channel close cancels in-flight RPCs as CANCELLED — not
    retried), and a retired replica that rejoins before draining is
    resurrected warm instead of redialed."""
    fc = _stub_fleet(["a:1", "b:1"])
    stub_a = fc._clients["a:1"]
    closed = []
    stub_a.close = lambda: closed.append("a:1")

    started = threading.Event()
    release = threading.Event()

    def slow_predict(features, timeout_s=30.0, lane="online"):
        started.set()
        release.wait(5.0)
        return {"outputs": [0.5], "model": "stub", "step": 0}

    stub_a.predict = slow_predict
    # Pin the pick: only a:1 is in the client map when the call starts.
    fc.set_replicas(["a:1"])
    t = threading.Thread(target=fc.predict, args=({"x": [1]},))
    t.start()
    assert started.wait(5.0)
    fc.set_replicas(["b:1"])  # a:1 retired mid-flight
    assert closed == []  # linger: close deferred, request unharmed
    assert fc.addresses() == ["b:1"]

    # Rejoin while lingering: same object back in the pick set, no redial.
    fc.set_replicas(["a:1", "b:1"])
    assert fc._clients["a:1"] is stub_a and fc._retired == {}

    # Retire again and let the request finish: LAST RIDER closes it.
    fc.set_replicas(["b:1"])
    release.set()
    t.join(5.0)
    assert closed == ["a:1"]
    assert "a:1" not in fc._inflight and "a:1" not in fc._retired
    fc.close()


# ------------------------------------------- in-process fleet (real jax)


def _wide_deep_tiny():
    # Trainer before the model zoo (zoo -> ops.embedding -> parallel ->
    # trainer import cycle resolves only in this order).
    import elasticdl_tpu.parallel.trainer  # noqa: F401
    from elasticdl_tpu.models.spec import load_model_spec

    return load_model_spec(
        "elasticdl_tpu.models", "wide_deep.model_spec",
        buckets=64, embedding_dim=4, hidden=(8,),
    )


def _features(n=1, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "dense": rng.rand(n, 5).astype(np.float32) * 50,
        "cat": rng.randint(0, 1 << 20, size=(n, 9)),
    }


def _replica_factory(spec, spawned, target_p99_ms=100.0):
    from elasticdl_tpu.serving.server import ServingServer

    def factory(slot):
        server = ServingServer(
            spec, max_batch=8, max_delay_ms=3, batch_buckets=(1, 2, 4),
            gauges=gaugelib.Registry(),  # own registry: per-replica scrapes
            gauge_port=0, target_p99_ms=target_p99_ms,
        )
        server.warmup()  # readiness implies compiled, like serving/main.py
        spawned.append(slot)
        return server.start()

    return factory


def test_fleet_smoke_scale_up_then_down(tmp_path, devices):
    """The tier-1 fleet smoke: 2 real replicas, a short live ramp blows a
    deliberately tight SLO -> scale to 3; idle windows -> scale back to 2.
    p2c spreads traffic over every replica and the bucketed-compile jitsan
    budgets hold fleet-wide (the sanitizer is armed suite-wide: one
    over-budget retrace anywhere fails this test loudly)."""
    spec = _wide_deep_tiny()
    spawned = []
    # SLO target below one batcher deadline: real traffic MUST blow it —
    # the scale-up below is driven by genuine latency, not a mock.
    backend = InProcessServingBackend(
        _replica_factory(spec, spawned, target_p99_ms=1.0)
    )
    ctl = ServingFleetController(
        backend, JobConfig(job_name="fleet-smoke"),
        state_path=str(tmp_path / "fleet-pods.json"),
        autoscale=AutoscaleConfig(
            min_replicas=2, max_replicas=3, poll_s=0.05, target_p99_ms=1.0,
            up_consecutive=2, down_consecutive=3, cooldown_polls=1,
        ),
        autoscale_enabled=False,  # poll_once-driven: deterministic in CI
        gauges=gaugelib.Registry(),
    )
    fc = None
    try:
        ctl.start(2)
        addrs = ctl.wait_ready(2, timeout_s=60.0)
        assert len(addrs) == 2 and spawned == [0, 1]
        fc = FleetServingClient(addrs, rng=random.Random(3))

        def burst(n=20):
            for i in range(n):
                r = fc.predict(_features(1, seed=i))
                assert r["model"] == "wide_deep" and len(r["outputs"]) == 1

        # Ramp up: real request latency (>= one 3 ms batcher deadline) vs
        # the 1 ms target -> up pressure two polls running -> scale 2->3.
        burst()
        d = ctl.poll_once()
        assert d["slo"] is not None and d["slo"] >= 1.0
        assert d["action"] == "" and d["up_streak"] == 1
        burst()
        d = ctl.poll_once()
        assert d["action"] == "up"
        assert ctl.pods.counts()["live"] == 3 and spawned == [0, 1, 2]
        addrs3 = ctl.wait_ready(3, timeout_s=60.0)
        fc.set_replicas(addrs3)
        burst()

        # Both lanes serve through the fleet front.
        out = fc.predict_outputs(_features(2, seed=99), lane="bulk")
        assert out.shape == (2,)
        # Unknown lane: structured schema error at the boundary, no retry.
        with pytest.raises(grpc.RpcError) as err:
            fc.predict(_features(1), lane="vip")
        assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION

        # Every replica answered (p2c spread), each on its own endpoint.
        from elasticdl_tpu.common.metrics_http import fetch

        for _name, _saddr, maddr in ctl.replicas():
            fams = fetch(maddr)
            served = sum(
                s["value"]
                for s in fams["edl_serving_requests_total"]["samples"]
            )
            assert served > 0, maddr
            # Bucketed compiles: flushes landed in declared buckets only.
            assert "edl_serving_bucket_flushes_total" in fams

        # Ramp down: idle windows read as no-signal -> down pressure ->
        # retire back to min after down_consecutive quiet polls.
        acts = [ctl.poll_once()["action"] for _ in range(8)]
        assert "down" in acts
        assert ctl.pods.counts()["live"] == 2
        fc.set_replicas(ctl.wait_ready(2, timeout_s=30.0))
        assert fc.predict(_features(1))["model"] == "wide_deep"

        # Scale events audit: exactly one up and one down, i.e. the loop
        # CONVERGED under the ramp instead of flapping.
        assert [(e["from"], e["to"]) for e in ctl.events()] == [
            (2, 3), (3, 2)
        ]

        # jitsan: every replica instance compiled at most its declared
        # bucket budget (buckets 1/2/4/8 -> budget 4 per instance).
        st = jitsan.stats().get("trainer.predict_step")
        assert st is not None and st["budget"] >= 4
    finally:
        if fc is not None:
            fc.close()
        ctl.stop()
        backend.close()


def test_fleet_controller_restart_adopts_live_replicas(tmp_path, devices):
    """r18 reattach, serving edition: a controller that dies WITHOUT
    stop() leaves replicas serving and the registry on disk; its
    replacement adopts the live fleet instead of spawning duplicates."""
    spec = _wide_deep_tiny()
    spawned = []
    backend = InProcessServingBackend(_replica_factory(spec, spawned))
    state = str(tmp_path / "fleet-pods.json")

    def controller():
        return ServingFleetController(
            backend, JobConfig(job_name="fleet-adopt"),
            state_path=state,
            autoscale=AutoscaleConfig(min_replicas=2, max_replicas=2),
            autoscale_enabled=False,
            gauges=gaugelib.Registry(),
        )

    ctl1 = controller()
    ctl2 = None
    try:
        ctl1.start(2)
        addrs1 = sorted(ctl1.wait_ready(2, timeout_s=60.0))
        assert len(spawned) == 2

        # Controller "crash": no stop(), no registry removal.  A second
        # controller over the same state_path re-owns the fleet.
        ctl2 = controller()
        ctl2.start(2)
        addrs2 = sorted(ctl2.wait_ready(2, timeout_s=30.0))
        assert addrs2 == addrs1      # the SAME live servers, same ports
        assert len(spawned) == 2     # adopted, not respawned
        assert ctl2.pods.counts()["live"] == 2

        # The adopted fleet serves: replicas rode the restart through.
        fc = FleetServingClient(addrs2)
        try:
            assert fc.predict(_features(1))["model"] == "wide_deep"
        finally:
            fc.close()
    finally:
        if ctl2 is not None:
            ctl2.stop()
        else:
            ctl1.stop()
        backend.close()
