"""graftreduce (parallel/collectives.py, r15): topology factorization,
hierarchical-vs-flat parity, subgroup exclusion renormalization (vs a
recomputed smaller-world baseline), recompile-free mask flips, elastic
reform with hierarchical mode on, the chaos ``point=collective`` grammar,
and the worker's in-step deadline gate end-to-end."""

import numpy as np
import pytest

import jax

from elasticdl_tpu import chaos
from elasticdl_tpu.common import trace
from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.parallel import collectives as coll
from elasticdl_tpu.parallel.mesh import create_mesh, dp_factorization
from elasticdl_tpu.parallel.trainer import Trainer


@pytest.fixture(autouse=True)
def _reset_chaos_and_trace():
    yield
    chaos.configure("")
    chaos.set_context(rank=None, worker_id=None, shard=None)
    trace.configure(enabled=False)
    trace.default().clear()


def _mnist_spec():
    return load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )


def _mnist_batch(n, seed=3):
    rng = np.random.default_rng(seed)
    return {
        "images": rng.uniform(size=(n, 28, 28, 1)).astype(np.float32),
        "labels": rng.integers(0, 10, (n,)).astype(np.int32),
    }


def _trainer(spec, n_dev, **cfg):
    config = JobConfig(**cfg)
    return Trainer(spec, config, create_mesh(jax.devices(), num_devices=n_dev))


def _max_param_diff(a, b):
    return max(
        float(np.max(np.abs(np.asarray(x) - np.asarray(y)))) if x.size else 0.0
        for x, y in zip(
            jax.tree.leaves(jax.device_get(a.params)),
            jax.tree.leaves(jax.device_get(b.params)),
        )
    )


# ---------------------------------------------------------------------------
# topology factorization + resolution
# ---------------------------------------------------------------------------

class TestFactorization:
    def test_single_host_is_trivial(self, devices):
        mesh = create_mesh(devices, num_devices=4)
        # All 8 fake devices share one process: no real grouping.
        assert dp_factorization(mesh) == (1, 4)

    def test_explicit_local_size(self, devices):
        mesh = create_mesh(devices, num_devices=8)
        assert dp_factorization(mesh, local_size=2) == (4, 2)
        assert dp_factorization(mesh, local_size=4) == (2, 4)

    def test_non_dividing_local_size_raises(self, devices):
        mesh = create_mesh(devices, num_devices=4)
        with pytest.raises(ValueError, match="does not divide"):
            dp_factorization(mesh, local_size=3)

    def test_resolve_flat_is_none(self, devices):
        mesh = create_mesh(devices, num_devices=4)
        assert coll.resolve_topology(mesh, ("dp",), mode="flat") is None

    def test_resolve_auto_single_host_is_flat(self, devices):
        mesh = create_mesh(devices, num_devices=4)
        assert coll.resolve_topology(mesh, ("dp",), mode="auto") is None

    def test_resolve_hierarchical_without_factorization_demotes(self, devices):
        # Explicit hierarchical with no grouping and no override: flat
        # fallback (availability beats layout — the elastic stance).
        mesh = create_mesh(devices, num_devices=4)
        assert coll.resolve_topology(mesh, ("dp",), mode="hierarchical") is None

    def test_resolve_with_override(self, devices):
        mesh = create_mesh(devices, num_devices=4)
        topo = coll.resolve_topology(
            mesh, ("dp",), mode="hierarchical", local_size=2, min_elems=1
        )
        assert topo is not None and topo.hierarchical
        assert (topo.n_host, topo.n_local) == (2, 2)
        assert topo.local_groups == [[0, 1], [2, 3]]
        assert topo.cross_groups == [[0, 2], [1, 3]]

    def test_interhost_bytes_model(self):
        topo = coll.CollectiveTopology("dp", n_host=2, n_local=4, min_elems=64)
        flat = coll.interhost_bytes_per_step([4096], 8, None)
        hier = coll.interhost_bytes_per_step([4096], 8, topo)
        # The inter-host residue is 1/n_local of the leaf: the cut the
        # hierarchy exists for.
        assert hier < flat / 3
        # Below min_elems both routes price flat.
        assert coll.interhost_bytes_per_step([16], 8, topo) == (
            coll.interhost_bytes_per_step([16], 8, None)
        )
        assert coll.interhost_bytes_per_step([4096], 1, topo) == 0


# ---------------------------------------------------------------------------
# hierarchical parity (flat vs 3-phase grouped reduce)
# ---------------------------------------------------------------------------

#: Float32 reduction-order tolerance (the r11 psum-vs-psum_scatter stance):
#: the hierarchical route sums in a different association order, so params
#: diverge by a few ulps per step, never more.
ULP_TOL = 5e-6


def test_hierarchical_train_parity(devices):
    spec = _mnist_spec()
    tf_ = _trainer(spec, 4, collective="flat")
    th = _trainer(
        spec, 4, collective="hierarchical", collective_local_size=2,
        collective_min_elems=1,
    )
    assert th.collective is not None and th.collective.hierarchical
    sf = tf_.init_state(jax.random.key(0))
    sh = th.init_state(jax.random.key(0))
    batch = _mnist_batch(64)
    for _ in range(3):
        sf, mf = tf_.train_step(sf, tf_.shard_batch(batch))
        sh, mh = th.train_step(sh, th.shard_batch(batch))
    assert _max_param_diff(sf, sh) < ULP_TOL
    assert abs(float(mf["loss"]) - float(mh["loss"])) < ULP_TOL


def test_hierarchical_with_sharded_optimizer(devices):
    # Composition with the r11 path: reduce-scatter grads + hierarchical
    # metric/table reductions in one step, vs the flat replicated build.
    spec = _mnist_spec()
    tf_ = _trainer(spec, 4, collective="flat")
    th = _trainer(
        spec, 4, collective="hierarchical", collective_local_size=2,
        collective_min_elems=1, optimizer_sharding="sharded",
    )
    sf = tf_.init_state(jax.random.key(0))
    sh = th.init_state(jax.random.key(0))
    batch = _mnist_batch(64)
    for _ in range(2):
        sf, _ = tf_.train_step(sf, tf_.shard_batch(batch))
        sh, _ = th.train_step(sh, th.shard_batch(batch))
    assert _max_param_diff(sf, sh) < ULP_TOL


def test_hierarchical_reform_2_4_2_preserves_moments(devices):
    # Elastic resize with hierarchical mode on: the canonical host bridge
    # is collective-mode-agnostic — moments survive 2->4->2 bit-exact
    # (r11's guarantee, now under the r15 topology), and the topology
    # re-resolves per mesh (4 devices factor 2x2; 2 devices cannot).
    spec = _mnist_spec()
    t = _trainer(
        spec, 4, collective="hierarchical", collective_local_size=2,
        collective_min_elems=1, optimizer_sharding="sharded",
    )
    state = t.init_state(jax.random.key(0))
    state, _ = t.train_step(state, t.shard_batch(_mnist_batch(64)))
    h0 = t.host_state(state)
    for size in (2, 4, 2):
        t.set_mesh(create_mesh(jax.devices(), num_devices=size))
        state = t.shard_state(h0)
        if size == 4:
            assert t.collective is not None and t.collective.hierarchical
        else:
            # local_size=2 over a 2-wide axis: n_host degenerates to 1.
            assert t.collective is None
        # The mask resets to the new mesh's contributor count.
        assert t.num_contributors() == size
        h1 = t.host_state(state)
        assert all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(h0), jax.tree.leaves(h1))
        )
    state, m = t.train_step(state, t.shard_batch(_mnist_batch(64)))
    assert np.isfinite(float(m["loss"]))


# ---------------------------------------------------------------------------
# subgroup exclusion: renormalization numerics + recompile-free mask
# ---------------------------------------------------------------------------

def test_excluded_rank_matches_smaller_world(devices):
    # sum/|G'| renormalization: a 4-shard step excluding shard 3 must
    # train exactly like a 1-device step over shards 0..2's examples
    # (float32 reduction-order tolerance, the r11 parity stance).
    spec = _mnist_spec()
    t4 = _trainer(spec, 4)
    t1 = _trainer(spec, 1)
    s4 = t4.init_state(jax.random.key(0))
    s1 = t1.init_state(jax.random.key(0))
    batch = _mnist_batch(64)
    t4.set_active_contributors([1, 1, 1, 0])
    s4, m4 = t4.train_step(s4, t4.shard_batch(batch))
    sub = {k: v[:48] for k, v in batch.items()}
    s1, m1 = t1.train_step(s1, t1.shard_batch(sub))
    assert _max_param_diff(s4, s1) < ULP_TOL
    assert abs(float(m4["loss"]) - float(m1["loss"])) < ULP_TOL


def test_excluded_rank_with_ragged_mask(devices):
    # Exclusion composes with the wrap-padded __mask__ weighting: the
    # renormalized total counts only ACTIVE shards' real examples.
    spec = _mnist_spec()
    t4 = _trainer(spec, 4)
    t1 = _trainer(spec, 1)
    batch = _mnist_batch(64)
    batch["__mask__"] = (np.arange(64) < 60).astype(np.float32)
    t4.set_active_contributors([0, 1, 1, 1])
    s4, m4 = t4.train_step(t4.init_state(jax.random.key(0)), t4.shard_batch(batch))
    sub = {k: v[16:] for k, v in batch.items()}
    s1, m1 = t1.train_step(t1.init_state(jax.random.key(0)), t1.shard_batch(sub))
    assert _max_param_diff(s4, s1) < ULP_TOL


def test_mask_flip_never_recompiles(devices):
    # Asserted via jitsan's lowering counters (common/jitsan.py, armed
    # suite-wide by conftest) instead of the r15 private jit cache probe:
    # the counter is the same signal production gauges/watch_job read, so
    # the test and the ops story can no longer drift.
    from elasticdl_tpu.common import jitsan

    if not jitsan.enabled():
        pytest.skip("jitsan disabled (GRAFT_JITSAN != 1)")
    spec = _mnist_spec()
    t = _trainer(spec, 4)
    state = t.init_state(jax.random.key(0))
    batch = _mnist_batch(64)
    state, _ = t.train_step(state, t.shard_batch(batch))  # warmup compile
    fn = t._train_step
    warm = jitsan.compiles("trainer.train_step")
    for mask in ([1, 1, 1, 0], [0, 1, 1, 1], None, [1, 0, 1, 1]):
        t.set_active_contributors(mask)
        state, _ = t.train_step(state, t.shard_batch(batch))
    assert t._train_step is fn  # same structural build
    # ZERO lowerings across every mask flip: the mask is a traced input.
    assert jitsan.compiles("trainer.train_step") == warm


def test_scan_variant_carries_mask(devices):
    # The fused lax.scan task path applies the same exclusion as the
    # per-step path: T scanned steps with shard 1 excluded equal T
    # per-step calls with the same mask.
    spec = _mnist_spec()
    ta = _trainer(spec, 2)
    tb = _trainer(spec, 2)
    sa = ta.init_state(jax.random.key(0))
    sb = tb.init_state(jax.random.key(0))
    stacked = {
        "images": np.stack([_mnist_batch(32, seed=s)["images"] for s in (1, 2)]),
        "labels": np.stack([_mnist_batch(32, seed=s)["labels"] for s in (1, 2)]),
    }
    ta.set_active_contributors([1, 0])
    tb.set_active_contributors([1, 0])
    sa, _ = ta.train_scan(sa, ta.shard_stacked_batch(stacked))
    for i in range(2):
        one = {k: v[i] for k, v in stacked.items()}
        sb, _ = tb.train_step(sb, tb.shard_batch(one))
    assert _max_param_diff(sa, sb) < ULP_TOL


def test_mask_validation(devices):
    t = _trainer(_mnist_spec(), 4)
    assert t.num_contributors() == 4
    with pytest.raises(ValueError, match="slots"):
        t.set_active_contributors([1, 1])
    with pytest.raises(ValueError, match="every contributor"):
        t.set_active_contributors([0, 0, 0, 0])
    t.set_active_contributors([1, 0, 1, 1])
    assert t.active_contributors().tolist() == [1, 0, 1, 1]
    t.set_active_contributors(None)
    assert t.active_contributors().tolist() == [1, 1, 1, 1]


def test_sequence_parallel_contributors_are_example_shards(devices):
    # A sequence-parallel model's inner-axis slices hold pieces of the
    # SAME examples: on a 1-D mesh there is no example sharding at all,
    # so exclusion must be unsupported (one contributor — the worker's
    # gate self-disables), and the mask input must be inert on the step.
    spec = load_model_spec(
        "elasticdl_tpu.models", "transformer_lm.model_spec",
        compute_dtype="float32", vocab=128, dim=32, n_heads=2, n_layers=1,
        max_seq=32, seq_len=32,
    )
    t = Trainer(spec, JobConfig(), create_mesh(jax.devices(), num_devices=2))
    assert spec.batch_shard_dim == 1
    assert t.contributor_axes == ()
    assert t.num_contributors() == 1
    with pytest.raises(ValueError, match="every contributor"):
        t.set_active_contributors([0])
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 128, size=(4, 33)).astype(np.int32)
    batch = {"tokens": toks[:, :-1], "labels": toks[:, 1:]}
    state = t.init_state(jax.random.key(0))
    state, m = t.run_train_step(state, batch)
    assert np.isfinite(float(m["loss"]))


def test_config_knobs_validate():
    JobConfig(collective="hierarchical", collective_local_size=2).validate()
    with pytest.raises(ValueError, match="--collective must"):
        JobConfig(collective="ring").validate()
    with pytest.raises(ValueError, match="collective_local_size"):
        JobConfig(collective_local_size=-1).validate()
    with pytest.raises(ValueError, match="collective_min_elems"):
        JobConfig(collective_min_elems=0).validate()
    with pytest.raises(ValueError, match="collective_deadline_ms"):
        JobConfig(collective_deadline_ms=-1.0).validate()
    # The config's literal mode list stays in sync with the module's.
    assert set(coll.MODES) == {"flat", "hierarchical", "auto"}


# ---------------------------------------------------------------------------
# chaos grammar: point=collective + shard addressing
# ---------------------------------------------------------------------------

class TestCollectiveChaosGrammar:
    def test_collective_stall_parses(self):
        from elasticdl_tpu.chaos.inject import parse_plan

        (f,) = parse_plan("stall:rank=0,point=collective,shard=1,ms=50")
        assert f.point == "collective" and f.shard == 1

    def test_shard_requires_collective_point(self):
        from elasticdl_tpu.chaos.inject import ChaosError, parse_plan

        with pytest.raises(ChaosError, match="shard"):
            parse_plan("stall:point=prep,shard=1,ms=50")

    def test_shard_gates_firing(self):
        from elasticdl_tpu.chaos.inject import ChaosInjector, parse_plan

        inj = ChaosInjector(
            parse_plan("stall:point=collective,shard=1,ms=1,count=0")
        )
        fired = []
        inj._apply = lambda f, p, c: fired.append(c.get("shard"))
        inj.fire("worker:collective", {"shard": 0})
        inj.fire("worker:collective", {"shard": 1})
        inj.fire("worker:collective", {"shard": 2})
        assert fired == [1]


# ---------------------------------------------------------------------------
# the worker's in-step deadline gate, end to end
# ---------------------------------------------------------------------------

def _run_gate_job(tmp_path, devices, chaos_plan, deadline_ms, tasks=4,
                  skip_budget=8):
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker

    train = str(tmp_path / "train.rio")
    generate("mnist", train, 32 * tasks)
    config = JobConfig(
        model_def="mnist.model_spec",
        training_data=train,
        minibatch_size=16,
        num_minibatches_per_task=2,
        trace=True,
        chaos=chaos_plan,
        collective_deadline_ms=deadline_ms,
        gang_skip_budget=skip_budget,
    )
    reader = create_data_reader(train)
    dispatcher = TaskDispatcher(reader.create_shards(32))
    servicer = MasterServicer(dispatcher)
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices[:2],
    )
    result = worker.run()
    return worker, servicer, result, tasks


def test_gate_excludes_stalled_shard_and_completes(tmp_path, devices):
    # Shard 1's contribution stalls 1.5 s at one gate crossing; the
    # 100 ms in-step deadline excludes it, the job NEVER waits the stall
    # out mid-task, every task completes exactly once, and the skip is
    # observable in the gauges, the trace, and the master's ledger.
    worker, servicer, result, tasks = _run_gate_job(
        tmp_path, devices,
        "stall:point=collective,shard=1,ms=1500,count=1",
        deadline_ms=100.0,
    )
    assert result["tasks_done"] == tasks
    status = servicer.JobStatus({})
    assert status["duplicate_done"] == 0 and not status.get("abandoned")
    assert worker._collective_skips >= 1
    # The master banked the heartbeat-borne ledger.
    assert status["collective_skips"].get("w0", 0) >= 1
    # Gauges: cumulative skip counter + live subgroup size family exist.
    snap = worker.gauges.snapshot()
    assert snap["edl_collective_skip_total"]["samples"][0]["value"] >= 1
    assert "edl_collective_subgroup_size" in snap
    assert snap["edl_collective_interhost_bytes_total"]["samples"][0]["value"] >= 0
    # Attributable: exclude (and, once the stall cleared, restore)
    # instants in the worker's ring.
    dump = servicer.DumpTrace({})
    names = [
        e["name"] for e in dump["processes"].get("w0", {}).get("events", [])
    ] + [e["name"] for e in trace.default().export()]
    assert "collective:exclude" in names
    assert "chaos:stall" in names


def test_gate_budget_escalates_to_waiting(tmp_path, devices):
    # gang_skip_budget=0: no free in-step skips — the gate must WAIT the
    # straggler out (the r13 bounded-skip stance: a dead contributor
    # surfaces as a visible stall, never silent exclusion forever).
    worker, servicer, result, tasks = _run_gate_job(
        tmp_path, devices,
        "stall:point=collective,shard=1,ms=400,count=1",
        deadline_ms=50.0, skip_budget=0,
    )
    assert result["tasks_done"] == tasks
    # The shard was never excluded past the budget: every crossing was
    # waited out, so no task trained without it after the charge.
    assert worker._collective_pending == {}
    # All contributors active again at job end.
    assert worker.trainer.active_contributors().sum() == 2


def test_gate_off_blocks_like_pre_r15(tmp_path, devices):
    # Deadline 0 (default): the stalled crossing blocks the dispatch —
    # nothing is excluded, nothing is skipped.
    worker, servicer, result, tasks = _run_gate_job(
        tmp_path, devices,
        "stall:point=collective,shard=1,ms=200,count=1",
        deadline_ms=0.0,
    )
    assert result["tasks_done"] == tasks
    assert worker._collective_skips == 0
    assert servicer.JobStatus({})["collective_skips"] == {}
