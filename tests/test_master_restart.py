"""Master-restart resume.

Two layers, two eras: the coarse task-progress watermark (SURVEY §5
"restore on master restart" — skip finished epochs, lose in-flight
shards) and, since r18, the durable control-plane journal
(master/journal.py): a restarted master replays the WAL to the EXACT
pre-crash dispatcher/servicer state — in-flight leases, the partially
consumed gang log, skip budgets, the report-seq dedup ledger — and
reconciles reconnecting workers' held leases against it."""

import json
import os
import sys
import threading
import time

import grpc
import numpy as np
import pytest

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.data.reader import Shard, create_data_reader
from elasticdl_tpu.data.synthetic import generate
from elasticdl_tpu.master import journal as journal_mod
from elasticdl_tpu.master.journal import MasterJournal
from elasticdl_tpu.master.main import Master
from elasticdl_tpu.master.pod_manager import FakePodBackend, ProcessPodBackend
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher


def _shards(n=6):
    return [Shard(name="d", start=i * 10, end=(i + 1) * 10) for i in range(n)]


class TestDispatcherResume:
    def test_resume_skips_done_shards(self):
        d1 = TaskDispatcher(_shards(4), num_epochs=2)
        for _ in range(3):
            t = d1.get_task("w")
            d1.report(t.task_id, success=True)
        progress = d1.progress()
        assert progress["epoch"] == 0 and len(progress["done_shards"]) == 3

        d2 = TaskDispatcher(_shards(4), num_epochs=2, resume=progress)
        assert d2.counts()["done"] == 3  # cumulative count carried over
        remaining = []
        while True:
            t = d2.get_task("w")
            if t is None:
                break
            remaining.append(t)
            d2.report(t.task_id, success=True)
        # 1 left in epoch 0 + the full second epoch.
        assert len(remaining) == 1 + 4
        assert remaining[0].epoch == 0 and remaining[1].epoch == 1
        assert d2.finished()

    def test_resume_fully_done_epoch_advances(self):
        # A watermark claiming every shard of epoch 0 done (in practice the
        # dispatcher advances the epoch on the last report, so this state
        # only persists at job END — but resume must handle it anyway).
        progress = {
            "epoch": 0,
            "done_shards": [["d", i * 10, (i + 1) * 10] for i in range(2)],
            "done_count": 2,
        }
        d2 = TaskDispatcher(_shards(2), num_epochs=2, resume=progress)
        tasks = []
        while True:
            t = d2.get_task("w")
            if t is None:
                break
            tasks.append(t)
            d2.report(t.task_id, success=True)
        assert [t.epoch for t in tasks] == [1, 1]
        assert d2.finished()

    def test_resume_complete_job_is_finished(self):
        d = TaskDispatcher(
            _shards(2), num_epochs=2,
            resume={"epoch": 2, "done_shards": [], "done_count": 4},
        )
        assert d.finished()
        assert d.get_task("w") is None


def _journaled_control_plane(tmp_path, n_shards=6, num_epochs=2):
    """A dispatcher + servicer pair recording into a WAL (the r18 shape
    Master wires up), plus the replay closure that rebuilds them."""
    path = str(tmp_path / "master_journal.wal")
    shards = _shards(n_shards)
    dispatcher = TaskDispatcher(shards, num_epochs=num_epochs)
    servicer = MasterServicer(dispatcher, rendezvous=RendezvousServer())
    j = MasterJournal(path)
    servicer.set_journal(j)
    dispatcher.attach_journal(j)
    servicer.rotate_journal()

    def replay():
        return journal_mod.replay(
            path, _shards(n_shards), num_epochs=num_epochs,
            task_type="training", task_timeout_s=600.0,
        )

    return dispatcher, servicer, path, replay


class TestJournalReplay:
    """The r18 tentpole contract: replay is BIT-IDENTICAL, torn tails
    tolerate, stale reports dedup, held leases reconcile."""

    def test_mid_job_replay_is_bit_identical(self, tmp_path):
        dispatcher, servicer, path, replay = _journaled_control_plane(tmp_path)
        servicer.RegisterWorker({"worker_id": "w1", "held_tasks": []})
        servicer.RegisterWorker({"worker_id": "w2", "held_tasks": []})
        # In-flight leases on two workers, successes, a failure (retry
        # budget charged), a requeue-flagged return, a worker loss.
        servicer.GetTask({"worker_id": "w1", "lease": 3})
        servicer.GetTask({"worker_id": "w2", "lease": 2})
        servicer.ReportTaskResult(
            {"worker_id": "w1", "task_id": 0, "success": True, "seq": 1,
             "model_version": 4}
        )
        servicer.ReportTaskResult(
            {"worker_id": "w2", "task_id": 3, "success": False, "seq": 1}
        )
        servicer.ReportTaskResult(
            {"worker_id": "w1", "task_id": 1, "success": False,
             "requeue": True, "seq": 2}
        )
        servicer.DeregisterWorker({"worker_id": "w2"})  # recover path
        snap = dispatcher.snapshot()
        counts = dispatcher.counts()

        replayed = replay()
        assert replayed.dispatcher.snapshot() == snap
        assert replayed.dispatcher.counts() == counts
        assert replayed.report_seqs == {"w1": 2, "w2": 1}
        assert replayed.model_version == 4
        # Membership versioning continues past the pre-crash value.
        assert replayed.membership_version >= 3

    def test_partially_consumed_gang_log_replays(self, tmp_path):
        dispatcher, servicer, path, replay = _journaled_control_plane(tmp_path)
        servicer.RegisterWorker({"worker_id": "g0"})
        servicer.RegisterWorker({"worker_id": "g1"})
        version = servicer.rendezvous.version()
        # Both members confirm the topology (the lockstep log withholds
        # collective tasks until the whole world agrees).
        servicer.Heartbeat({"worker_id": "g0", "version": version})
        servicer.Heartbeat({"worker_id": "g1", "version": version})
        # Both ranks walk the lockstep log; rank 1 lags at seq 1.
        r0 = servicer.GetGroupTask(
            {"worker_id": "g0", "seq": 0, "version": version, "lease": 2}
        )
        assert not r0["stale"] and len(r0["entries"]) == 2
        servicer.GetGroupTask(
            {"worker_id": "g1", "seq": 0, "version": version}
        )
        group_worker = servicer.group_worker_id(version)
        servicer.ReportTaskResult(
            {"worker_id": group_worker, "task_id": 0, "success": True}
        )
        snap = dispatcher.snapshot()
        with servicer._group_lock:
            log_before = [dict(e) for e in servicer._group_log]

        replayed = replay()
        assert replayed.dispatcher.snapshot() == snap
        assert replayed.group_version == version
        assert replayed.group_log == log_before
        # A new servicer adopting the replay serves the SAME seq walk.
        s2 = MasterServicer(replayed.dispatcher, rendezvous=RendezvousServer())
        s2.adopt_replayed(replayed)
        s2.rendezvous.seed_version(replayed.membership_version)
        with s2._group_lock:
            assert s2._group_log == log_before
            assert s2._group_version == version

    def test_torn_final_line_tolerated_mid_file_garbage_raises(self, tmp_path):
        dispatcher, servicer, path, replay = _journaled_control_plane(tmp_path)
        servicer.RegisterWorker({"worker_id": "w1", "held_tasks": []})
        servicer.GetTask({"worker_id": "w1", "lease": 2})
        snap = dispatcher.snapshot()
        # Torn FINAL line: a crash mid-append (the r12 MetricsWriter
        # stance) — replay succeeds on the prefix.
        with open(path, "ab") as f:
            f.write(b'{"kind": "repo')
        replayed = replay()
        assert replayed.torn_tail
        assert replayed.dispatcher.snapshot() == snap
        # Mid-file garbage is corruption, not a crash tail: loud failure.
        lines = open(path, "rb").read().split(b"\n")
        lines.insert(1, b"\x00GARBAGE\x00")
        with open(path, "wb") as f:
            f.write(b"\n".join(lines))
        with pytest.raises(journal_mod.JournalError):
            replay()

    def test_stale_pre_restart_report_rejected_exactly_once(self, tmp_path):
        dispatcher, servicer, path, replay = _journaled_control_plane(tmp_path)
        servicer.RegisterWorker({"worker_id": "w1", "held_tasks": []})
        servicer.GetTask({"worker_id": "w1", "lease": 2})
        report = {
            "worker_id": "w1", "task_id": 0, "success": True, "seq": 1,
        }
        assert servicer.ReportTaskResult(dict(report))["accepted"]
        counts = dispatcher.counts()

        replayed = replay()
        s2 = MasterServicer(replayed.dispatcher, rendezvous=RendezvousServer())
        s2.adopt_replayed(replayed)
        # The proxy's ride-through re-sends the pre-restart report (the
        # old master died before answering): deduped by seq — accepted to
        # the worker, applied to nothing, duplicate_done untouched.
        resp = s2.ReportTaskResult(dict(report))
        assert resp["accepted"] and resp.get("duplicate") is True
        after = replayed.dispatcher.counts()
        assert after == counts
        assert after["duplicate_done"] == 0
        status = s2.JobStatus({})
        assert status["stale_reports"] == 1
        assert status["journal"]["replayed_events"] > 0
        # A FRESH seq for the same already-gone task keeps the r13
        # late-success accounting: rejected and counted there.
        resp = s2.ReportTaskResult(dict(report, seq=2))
        assert not resp["accepted"]
        assert replayed.dispatcher.counts()["duplicate_done"] == 1

    def test_fresh_incarnation_resets_seq_ledger(self, tmp_path):
        """A RESPAWNED worker restarts its seq counter at 1; under the
        replayed ledger its first reports would dedup as pre-restart
        duplicates and silently drop — a changed incarnation resets the
        ledger (the ride-through case is ordering-safe: the retried
        report dedups BEFORE the reconcile re-registration runs)."""
        dispatcher, servicer, path, replay = _journaled_control_plane(tmp_path)
        servicer.RegisterWorker(
            {"worker_id": "w1", "incarnation": "life-1", "held_tasks": []}
        )
        servicer.GetTask({"worker_id": "w1", "lease": 2})
        for seq, tid in ((1, 0), (2, 1)):
            servicer.ReportTaskResult(
                {"worker_id": "w1", "task_id": tid, "success": True,
                 "seq": seq}
            )
        replayed = replay()
        assert replayed.report_seqs == {"w1": 2}
        s2 = MasterServicer(replayed.dispatcher, rendezvous=RendezvousServer())
        s2.adopt_replayed(replayed)
        # Whole-job restart: a NEW incarnation of the same id registers.
        s2.RegisterWorker(
            {"worker_id": "w1", "incarnation": "life-2", "held_tasks": []}
        )
        s2.GetTask({"worker_id": "w1", "lease": 1})
        done_before = replayed.dispatcher.counts()["done"]
        resp = s2.ReportTaskResult(
            {"worker_id": "w1", "task_id": 2, "success": True, "seq": 1}
        )
        assert resp["accepted"] and not resp.get("duplicate")
        assert replayed.dispatcher.counts()["done"] == done_before + 1
        # Same incarnation re-registering does NOT reset (reconnect path).
        s2.GetTask({"worker_id": "w1", "lease": 1})
        s2.ReportTaskResult(
            {"worker_id": "w1", "task_id": 3, "success": True, "seq": 2}
        )
        s2.RegisterWorker(
            {"worker_id": "w1", "incarnation": "life-2", "held_tasks": []}
        )
        dup = s2.ReportTaskResult(
            {"worker_id": "w1", "task_id": 3, "success": True, "seq": 2}
        )
        assert dup.get("duplicate") is True

    def test_lease_reconcile_requeues_lost_and_names_stale(self, tmp_path):
        dispatcher, servicer, path, replay = _journaled_control_plane(tmp_path)
        servicer.RegisterWorker({"worker_id": "w1", "held_tasks": []})
        servicer.GetTask({"worker_id": "w1", "lease": 3})  # leases 0,1,2
        servicer.ReportTaskResult(
            {"worker_id": "w1", "task_id": 0, "success": True, "seq": 1}
        )
        replayed = replay()
        s2 = MasterServicer(replayed.dispatcher, rendezvous=RendezvousServer())
        s2.adopt_replayed(replayed)
        # Re-attach the WAL (the Master wiring) so the reconcile journals.
        replayed.dispatcher.attach_journal(MasterJournal(path))
        # The reconnecting worker still holds 1 and (wrongly) claims 0.
        resp = s2.RegisterWorker(
            {"worker_id": "w1", "incarnation": "x-1",
             "held_tasks": [0, 1]}
        )
        # 2 was lost in flight -> requeued now; 0 is stale (already done).
        assert resp["stale_tasks"] == [0]
        counts = replayed.dispatcher.counts()
        assert counts["doing"] == 1  # only the held task 1 stays leased
        # The reconcile itself was journaled: a SECOND replay agrees.
        replayed2 = replay()
        assert replayed2.dispatcher.counts() == counts

    def test_master_level_journal_restart(self, tmp_path):
        """Master-level: a second Master over the same checkpoint_dir
        restores the exact dispatcher state (not the watermark's
        epoch-granularity approximation) and stamps its restart."""
        data = str(tmp_path / "train.rio")
        generate("mnist", data, 96)  # 6 tasks of 16

        def config():
            return JobConfig(
                job_name="journaljob",
                model_def="mnist.model_spec",
                training_data=data,
                minibatch_size=16,
                num_minibatches_per_task=1,
                checkpoint_dir=str(tmp_path / "ckpt"),
                pod_backend="fake",
            )

        m1 = Master(config(), pod_backend=FakePodBackend())
        m1.servicer.RegisterWorker({"worker_id": "w1", "held_tasks": []})
        m1.servicer.GetTask({"worker_id": "w1", "lease": 2})
        m1.servicer.ReportTaskResult(
            {"worker_id": "w1", "task_id": 0, "success": True, "seq": 1}
        )
        snap = m1.dispatcher.snapshot()
        # No shutdown: the "crash".  (The journal fd needs no close to be
        # durable — every record was fsynced.)
        m2 = Master(config(), pod_backend=FakePodBackend())
        assert m2.dispatcher.snapshot() == snap
        status = m2.servicer.JobStatus({})
        assert status["journal"]["restarts"] == 1
        assert status["journal"]["replayed_events"] > 0
        assert m2.rendezvous.version() >= m1.rendezvous.version()
        m1.shutdown()
        m2.shutdown()

    def test_whole_job_restart_replays_base_only(self, tmp_path):
        """A pod registry POSITIVELY showing the fleet dead means the
        workers will restore the MODEL from the checkpoint: the journal's
        post-checkpoint events describe updates that died with them, so
        the restart replays the checkpoint-coupled BASE only and the
        skipped tail re-trains (at-least-once, never silent skip)."""
        data = str(tmp_path / "train.rio")
        generate("mnist", data, 96)

        def config():
            return JobConfig(
                job_name="coldjob",
                model_def="mnist.model_spec",
                training_data=data,
                minibatch_size=16,
                num_minibatches_per_task=1,
                checkpoint_dir=str(tmp_path / "ckpt"),
                pod_backend="fake",
            )

        m1 = Master(config(), pod_backend=FakePodBackend())
        base_snap = m1.dispatcher.snapshot()  # the __init__ rotation base
        m1.servicer.RegisterWorker({"worker_id": "w1", "held_tasks": []})
        m1.servicer.GetTask({"worker_id": "w1", "lease": 2})
        m1.servicer.ReportTaskResult(
            {"worker_id": "w1", "task_id": 0, "success": True, "seq": 1}
        )
        # The registry says the fleet existed and is now DEAD.
        json.dump(
            {"slots": {"0": {"name": "coldjob-worker-0",
                             "pid": 2 ** 22 + 4321}}},
            open(tmp_path / "ckpt" / "pod_registry.json", "w"),
        )
        m2 = Master(config(), pod_backend=FakePodBackend())
        assert m2.dispatcher.snapshot() == base_snap  # done=1 NOT skipped
        assert m2.dispatcher.counts()["done"] == 0
        m1.shutdown()
        m2.shutdown()

    def test_incarnation_reset_survives_replay(self, tmp_path):
        """The ledger reset is journaled: a replay must NOT max() a dead
        incarnation's high seq back over the fresh incarnation's low
        seqs (which would wrongly dedup its in-flight retried report)."""
        dispatcher, servicer, path, replay = _journaled_control_plane(tmp_path)
        servicer.RegisterWorker(
            {"worker_id": "w1", "incarnation": "life-A", "held_tasks": []}
        )
        servicer.GetTask({"worker_id": "w1", "lease": 1})
        servicer.ReportTaskResult(
            {"worker_id": "w1", "task_id": 0, "success": True, "seq": 57}
        )
        # Respawn: fresh incarnation, counter restarts at 1.
        servicer.RegisterWorker(
            {"worker_id": "w1", "incarnation": "life-B", "held_tasks": []}
        )
        servicer.GetTask({"worker_id": "w1", "lease": 1})
        servicer.ReportTaskResult(
            {"worker_id": "w1", "task_id": 1, "success": True, "seq": 1}
        )
        replayed = replay()
        assert replayed.report_seqs == {"w1": 1}  # NOT 57
        assert replayed.incarnations["w1"] == "life-B"
        s2 = MasterServicer(replayed.dispatcher, rendezvous=RendezvousServer())
        s2.adopt_replayed(replayed)
        s2.GetTask({"worker_id": "w1", "lease": 1})
        resp = s2.ReportTaskResult(
            {"worker_id": "w1", "task_id": 2, "success": True, "seq": 2}
        )
        assert resp["accepted"] and not resp.get("duplicate")

    def test_full_replay_keeps_base_checkpoint_coupled(self, tmp_path):
        """A master-only restart (full replay) must NOT rotate the WAL at
        startup: the base has to stay the last checkpoint-coupled
        snapshot, or a LATER whole-node restart's base-only mode would
        trust replayed in-memory progress as checkpoint-consistent."""
        data = str(tmp_path / "train.rio")
        generate("mnist", data, 96)

        def config():
            return JobConfig(
                job_name="chainjob",
                model_def="mnist.model_spec",
                training_data=data,
                minibatch_size=16,
                num_minibatches_per_task=1,
                checkpoint_dir=str(tmp_path / "ckpt"),
                pod_backend="fake",
            )

        m1 = Master(config(), pod_backend=FakePodBackend())
        base_snap = m1.dispatcher.snapshot()  # checkpoint-coupled base
        m1.servicer.RegisterWorker({"worker_id": "w1", "held_tasks": []})
        m1.servicer.GetTask({"worker_id": "w1", "lease": 1})
        m1.servicer.ReportTaskResult(
            {"worker_id": "w1", "task_id": 0, "success": True, "seq": 1}
        )
        # Master-only restart chain: each full replay continues the WAL.
        m2 = Master(config(), pod_backend=FakePodBackend())
        assert m2.dispatcher.counts()["done"] == 1
        m2.servicer.GetTask({"worker_id": "w1", "lease": 1})
        m2.servicer.ReportTaskResult(
            {"worker_id": "w1", "task_id": 1, "success": True, "seq": 2}
        )
        m3 = Master(config(), pod_backend=FakePodBackend())
        assert m3.dispatcher.counts()["done"] == 2  # events chain across gens
        assert m3.servicer.JobStatus({})["journal"]["restarts"] == 2
        # Whole node dies: the fleet is positively gone.
        json.dump(
            {"slots": {"0": {"name": "chainjob-worker-0",
                             "pid": 2 ** 22 + 77}}},
            open(tmp_path / "ckpt" / "pod_registry.json", "w"),
        )
        m4 = Master(config(), pod_backend=FakePodBackend())
        # Base-only lands on the ORIGINAL checkpoint-coupled base — not
        # m2/m3's replayed in-memory progress.
        assert m4.dispatcher.snapshot() == base_snap
        assert m4.dispatcher.counts()["done"] == 0
        for m in (m1, m2, m3, m4):
            m.shutdown()

    def test_restarted_master_disarms_master_kill(self, tmp_path):
        """The worker-kill family's incarnation guard, mirrored: a
        relaunched master under the SAME chaos plan must not re-fire the
        kill that already satisfied step=N."""
        from elasticdl_tpu import chaos

        data = str(tmp_path / "train.rio")
        generate("mnist", data, 96)

        def config():
            return JobConfig(
                job_name="rekill",
                model_def="mnist.model_spec",
                training_data=data,
                minibatch_size=16,
                num_minibatches_per_task=1,
                checkpoint_dir=str(tmp_path / "ckpt"),
                pod_backend="fake",
                chaos="kill:target=master,step=1",
            )

        try:
            m1 = Master(config(), pod_backend=FakePodBackend())
            assert any(
                f["kind"] == "kill" for f in chaos.default().stats()
            )
            m1.servicer.RegisterWorker({"worker_id": "w1", "held_tasks": []})
            m1.servicer.GetTask({"worker_id": "w1", "lease": 1})
            # (No real kill: chaos._INJ._exit is the real os._exit; the
            # report below WOULD fire it — so drive the dispatcher
            # directly instead and just prove the restart disarms.)
            m1.dispatcher.report(0, True, "w1", seq=1)
            m2 = Master(config(), pod_backend=FakePodBackend())
            assert not any(
                f["kind"] == "kill" and f["target"] == "master"
                for f in chaos.default().stats()
            )
            m1.shutdown()
            m2.shutdown()
        finally:
            chaos.configure("")  # never leak an armed plan into the suite


class TestProxyRideThrough:
    """RpcMasterProxy's outage reconnect against a REAL gRPC master."""

    def test_call_rides_out_a_master_restart(self, tmp_path):
        from elasticdl_tpu.master.servicer import MasterServer
        from elasticdl_tpu.worker.worker import RpcMasterProxy

        dispatcher = TaskDispatcher(_shards(4))
        servicer = MasterServicer(dispatcher, rendezvous=RendezvousServer())
        server = MasterServer(servicer, port=0)
        server.start()
        port = server.port
        proxy = RpcMasterProxy(
            f"localhost:{port}", timeout_s=10.0, outage_tolerance_s=30.0
        )
        assert proxy.call("GetMembership", {})["version"] == 0
        assert not proxy.take_reconnected()
        server.stop(grace=0)
        time.sleep(0.2)

        result = {}

        def _blocked_call():
            result["resp"] = proxy.call(
                "RegisterWorker", {"worker_id": "w1", "held_tasks": []}
            )

        t = threading.Thread(target=_blocked_call, daemon=True)
        t.start()
        time.sleep(1.0)
        assert t.is_alive(), "call should be parked in the outage backoff"
        # Master "restarts" on the same port.
        server2 = MasterServer(servicer, port=port)
        server2.start()
        try:
            t.join(timeout=30)
            assert not t.is_alive()
            assert result["resp"]["version"] >= 1
            assert proxy.take_reconnected()
            assert not proxy.take_reconnected()  # one handshake per outage
        finally:
            server2.stop(grace=0)

    def test_outage_tolerance_is_terminal(self):
        from elasticdl_tpu.worker.worker import RpcMasterProxy
        from elasticdl_tpu.common.platform import free_port

        # A port nothing listens on: wait_ready inside __init__ must fail
        # with the clear terminal error, inside a bounded budget.
        port = free_port()
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="not reachable"):
            RpcMasterProxy(f"localhost:{port}", timeout_s=2.0)
        assert time.monotonic() - t0 < 20.0


class TestSharedBackoffHelper:
    def test_retries_then_succeeds_and_counts(self):
        from elasticdl_tpu.common import gauge as gaugelib
        from elasticdl_tpu.common.rpc import BackoffPolicy, call_with_backoff

        calls = {"n": 0}
        sleeps = []

        def fn():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        before = _retry_total("unittest")
        out = call_with_backoff(
            fn, service="unittest",
            is_transient=lambda e: isinstance(e, OSError),
            policy=BackoffPolicy(
                base_s=0.01, max_s=0.04, jitter=0.0, max_attempts=5
            ),
            sleep=sleeps.append,
        )
        assert out == "ok" and calls["n"] == 3
        assert sleeps == [0.01, 0.02]  # exponential, jitter-free
        assert _retry_total("unittest") == before + 2

    def test_non_transient_surfaces_immediately(self):
        from elasticdl_tpu.common.rpc import BackoffPolicy, call_with_backoff

        with pytest.raises(ValueError):
            call_with_backoff(
                lambda: (_ for _ in ()).throw(ValueError("real")),
                service="unittest",
                is_transient=lambda e: isinstance(e, OSError),
                policy=BackoffPolicy(max_attempts=5),
            )

    def test_exhaustion_raises_terminal_from_original(self):
        from elasticdl_tpu.common.rpc import BackoffPolicy, call_with_backoff

        def fn():
            raise OSError("down")

        with pytest.raises(RuntimeError, match="gave up") as ei:
            call_with_backoff(
                fn, service="unittest",
                is_transient=lambda e: isinstance(e, OSError),
                policy=BackoffPolicy(base_s=0.0, jitter=0.0, max_attempts=2),
                terminal=lambda e, n, t: RuntimeError(f"gave up after {n}"),
                sleep=lambda s: None,
            )
        assert isinstance(ei.value.__cause__, OSError)

    def test_dynamic_budget_of_zero_exhausts_immediately(self):
        from elasticdl_tpu.common.rpc import BackoffPolicy, call_with_backoff

        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            raise OSError("down")

        # A dynamic budget is ALWAYS active: 0 means exhausted now — the
        # preemption path shrinking an in-flight ride-through must fail
        # it fast, never unbound it (a static budget_s=0 means no wall
        # budget, by contrast).
        with pytest.raises(OSError):
            call_with_backoff(
                fn, service="unittest",
                is_transient=lambda e: isinstance(e, OSError),
                policy=BackoffPolicy(jitter=0.0),
                budget_s_fn=lambda: 0.0,
                sleep=lambda s: None,
            )
        assert calls["n"] == 1

    def test_wall_budget_bounds_the_loop(self):
        from elasticdl_tpu.common.rpc import BackoffPolicy, call_with_backoff

        clock = {"t": 0.0}

        def fn():
            raise OSError("down")

        def sleep(s):
            clock["t"] += s

        with pytest.raises(OSError):
            call_with_backoff(
                fn, service="unittest",
                is_transient=lambda e: isinstance(e, OSError),
                policy=BackoffPolicy(
                    base_s=1.0, max_s=4.0, jitter=0.0, budget_s=10.0
                ),
                sleep=sleep, clock=lambda: clock["t"],
            )
        assert clock["t"] <= 10.0


def _retry_total(service: str) -> float:
    from elasticdl_tpu.common import gauge as gaugelib

    fam = gaugelib.default().snapshot().get("edl_rpc_retry_total") or {}
    for s in fam.get("samples", []):
        if s.get("labels", {}).get("service") == service:
            return s["value"]
    return 0.0


@pytest.mark.slow
def test_master_restart_resumes_job(tmp_path):
    """Kill the master mid-job; a new master over the same checkpoint_dir
    dispatches ONLY the remaining tasks and the job completes with every
    task done exactly once."""
    data = str(tmp_path / "train.rio")
    generate("mnist", data, 160)  # 10 tasks of 16

    WORKER = f"""
import os, sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
from elasticdl_tpu.worker.main import main
sys.exit(main())
"""
    entry = tmp_path / "w.py"
    entry.write_text(WORKER)

    def config():
        return JobConfig(
            job_name="restartjob",
            model_def="mnist.model_spec",
            model_params="compute_dtype=float32",
            training_data=data,
            minibatch_size=16,
            num_minibatches_per_task=1,
            checkpoint_dir=str(tmp_path / "ckpt"),
            checkpoint_steps=2,
        )

    m1 = Master(
        config(),
        pod_backend=ProcessPodBackend(argv=[sys.executable, str(entry)]),
    )
    result = {}
    t = threading.Thread(
        target=lambda: result.update(status=m1.run(poll_interval_s=0.05)),
        daemon=True,
    )
    t.start()
    # Kill only once the WATERMARK exists (it persists at ReportCheckpoint,
    # which lags the task report by the checkpoint save — waiting on the
    # done count alone raced that save under load).
    progress_path = tmp_path / "ckpt" / "job_progress.json"
    deadline = time.time() + 120
    while time.time() < deadline:
        if progress_path.exists() and m1.servicer.JobStatus({})["done"] >= 2:
            break
        time.sleep(0.1)
    m1.shutdown()  # the "crash": kills workers, stops the server
    t.join(timeout=30)
    done_at_kill = m1.servicer.JobStatus({})["done"]
    assert done_at_kill > 0, "job never progressed"
    assert progress_path.exists(), "watermark never persisted"

    m2 = Master(
        config(),
        pod_backend=ProcessPodBackend(argv=[sys.executable, str(entry)]),
    )
    # The restarted dispatcher created only the REMAINING epoch-0 tasks.
    import json

    persisted = json.loads(progress_path.read_text())
    remaining = 10 - len(persisted["done_shards"])
    assert m2.dispatcher.counts()["todo"] == remaining
    status = m2.run(poll_interval_s=0.05)
    assert status["finished"]
    # Cumulative done covers every task exactly once (persisted + new).
    assert status["done"] == len(persisted["done_shards"]) + remaining == 10
