"""Master-restart resume (SURVEY §5 "restore on master restart"): the task
watermark persists to checkpoint_dir; a restarted master skips finished work
instead of re-running the epoch from the top."""

import os
import sys
import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.data.reader import Shard, create_data_reader
from elasticdl_tpu.data.synthetic import generate
from elasticdl_tpu.master.main import Master
from elasticdl_tpu.master.pod_manager import ProcessPodBackend
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher


def _shards(n=6):
    return [Shard(name="d", start=i * 10, end=(i + 1) * 10) for i in range(n)]


class TestDispatcherResume:
    def test_resume_skips_done_shards(self):
        d1 = TaskDispatcher(_shards(4), num_epochs=2)
        for _ in range(3):
            t = d1.get_task("w")
            d1.report(t.task_id, success=True)
        progress = d1.progress()
        assert progress["epoch"] == 0 and len(progress["done_shards"]) == 3

        d2 = TaskDispatcher(_shards(4), num_epochs=2, resume=progress)
        assert d2.counts()["done"] == 3  # cumulative count carried over
        remaining = []
        while True:
            t = d2.get_task("w")
            if t is None:
                break
            remaining.append(t)
            d2.report(t.task_id, success=True)
        # 1 left in epoch 0 + the full second epoch.
        assert len(remaining) == 1 + 4
        assert remaining[0].epoch == 0 and remaining[1].epoch == 1
        assert d2.finished()

    def test_resume_fully_done_epoch_advances(self):
        # A watermark claiming every shard of epoch 0 done (in practice the
        # dispatcher advances the epoch on the last report, so this state
        # only persists at job END — but resume must handle it anyway).
        progress = {
            "epoch": 0,
            "done_shards": [["d", i * 10, (i + 1) * 10] for i in range(2)],
            "done_count": 2,
        }
        d2 = TaskDispatcher(_shards(2), num_epochs=2, resume=progress)
        tasks = []
        while True:
            t = d2.get_task("w")
            if t is None:
                break
            tasks.append(t)
            d2.report(t.task_id, success=True)
        assert [t.epoch for t in tasks] == [1, 1]
        assert d2.finished()

    def test_resume_complete_job_is_finished(self):
        d = TaskDispatcher(
            _shards(2), num_epochs=2,
            resume={"epoch": 2, "done_shards": [], "done_count": 4},
        )
        assert d.finished()
        assert d.get_task("w") is None


@pytest.mark.slow
def test_master_restart_resumes_job(tmp_path):
    """Kill the master mid-job; a new master over the same checkpoint_dir
    dispatches ONLY the remaining tasks and the job completes with every
    task done exactly once."""
    data = str(tmp_path / "train.rio")
    generate("mnist", data, 160)  # 10 tasks of 16

    WORKER = f"""
import os, sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
from elasticdl_tpu.worker.main import main
sys.exit(main())
"""
    entry = tmp_path / "w.py"
    entry.write_text(WORKER)

    def config():
        return JobConfig(
            job_name="restartjob",
            model_def="mnist.model_spec",
            model_params="compute_dtype=float32",
            training_data=data,
            minibatch_size=16,
            num_minibatches_per_task=1,
            checkpoint_dir=str(tmp_path / "ckpt"),
            checkpoint_steps=2,
        )

    m1 = Master(
        config(),
        pod_backend=ProcessPodBackend(argv=[sys.executable, str(entry)]),
    )
    result = {}
    t = threading.Thread(
        target=lambda: result.update(status=m1.run(poll_interval_s=0.05)),
        daemon=True,
    )
    t.start()
    # Kill only once the WATERMARK exists (it persists at ReportCheckpoint,
    # which lags the task report by the checkpoint save — waiting on the
    # done count alone raced that save under load).
    progress_path = tmp_path / "ckpt" / "job_progress.json"
    deadline = time.time() + 120
    while time.time() < deadline:
        if progress_path.exists() and m1.servicer.JobStatus({})["done"] >= 2:
            break
        time.sleep(0.1)
    m1.shutdown()  # the "crash": kills workers, stops the server
    t.join(timeout=30)
    done_at_kill = m1.servicer.JobStatus({})["done"]
    assert done_at_kill > 0, "job never progressed"
    assert progress_path.exists(), "watermark never persisted"

    m2 = Master(
        config(),
        pod_backend=ProcessPodBackend(argv=[sys.executable, str(entry)]),
    )
    # The restarted dispatcher created only the REMAINING epoch-0 tasks.
    import json

    persisted = json.loads(progress_path.read_text())
    remaining = 10 - len(persisted["done_shards"])
    assert m2.dispatcher.counts()["todo"] == remaining
    status = m2.run(poll_interval_s=0.05)
    assert status["finished"]
    # Cumulative done covers every task exactly once (persisted + new).
    assert status["done"] == len(persisted["done_shards"]) + remaining == 10
