"""Offline PS snapshot resharding (ps/reshard.py): rows AND optimizer slots
survive fleet resizes bit-for-bit."""

import os

import numpy as np
import pytest

from elasticdl_tpu.models.spec import HostTableIO
from elasticdl_tpu.ps import PSServer, RemoteEmbeddingStore
from elasticdl_tpu.ps.reshard import read_snapshot, reshard_step
from elasticdl_tpu.ps.service import snapshot_filename


def _native_available() -> bool:
    from elasticdl_tpu.ps.host_store import native_lib_available

    return native_lib_available()


needs_native = pytest.mark.skipif(
    not _native_available(), reason="native lib unavailable"
)

IO = HostTableIO(
    ids_fn=lambda b: b, dim=8, optimizer="adagrad", learning_rate=0.3
)


def _trained_fleet_snapshot(tmp_path, n_shards, step=7):
    """Train a fleet a little (so optimizer slots are nonzero), snapshot."""
    servers = [
        PSServer({"t": IO}, shard=s, num_shards=n_shards).start()
        for s in range(n_shards)
    ]
    store = RemoteEmbeddingStore("t", IO.dim, [s.address for s in servers])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 10_000, size=(256,)).astype(np.int64)
    for k in range(3):
        store.push_grad(ids, rng.randn(ids.size, IO.dim).astype(np.float32))
    probe = np.arange(64, dtype=np.int64)
    rows = store.pull(probe)
    store.save_snapshot(str(tmp_path), step=step)
    store.close()
    for s in servers:
        s.stop()
    return ids, probe, rows


def _fleet_rows(tmp_path, n_shards, probe, step=7):
    servers = [PSServer({"t": IO}, shard=s, num_shards=n_shards)
               for s in range(n_shards)]
    assert all(s.restore_latest(str(tmp_path)) == step for s in servers)
    for s in servers:
        s.start()
    store = RemoteEmbeddingStore("t", IO.dim, [s.address for s in servers])
    rows = store.pull(probe)
    store.close()
    for s in servers:
        s.stop()
    return rows


@needs_native
@pytest.mark.parametrize("old_n,new_n", [(1, 3), (3, 1), (2, 4)])
def test_reshard_preserves_rows(tmp_path, old_n, new_n):
    ids, probe, rows_before = _trained_fleet_snapshot(tmp_path, old_n)
    counts = reshard_step(str(tmp_path), step=7, new_shards=new_n,
                          prune_old=True)
    # The probe pull lazily materialized its rows too before the save.
    assert counts["t"] == np.unique(np.concatenate([ids, probe])).size
    step_dir = tmp_path / "host_stores" / "7"
    names = sorted(os.listdir(step_dir))
    assert names == sorted(
        snapshot_filename("t", j, new_n) for j in range(new_n)
    )
    rows_after = _fleet_rows(tmp_path, new_n, probe)
    np.testing.assert_array_equal(rows_after, rows_before)


@needs_native
def test_reshard_preserves_optimizer_state(tmp_path):
    """Training CONTINUES identically after a reshard: adagrad accumulators
    moved with the rows, so the next push applies the same effective lr."""
    ids, probe, _ = _trained_fleet_snapshot(tmp_path, 1)
    rng = np.random.RandomState(42)
    next_grads = rng.randn(ids.size, IO.dim).astype(np.float32)

    def continue_training(n_shards):
        servers = [PSServer({"t": IO}, shard=s, num_shards=n_shards)
                   for s in range(n_shards)]
        for s in servers:
            s.restore_latest(str(tmp_path))
            s.start()
        store = RemoteEmbeddingStore("t", IO.dim, [s.address for s in servers])
        store.push_grad(ids, next_grads)
        rows = store.pull(probe)
        store.close()
        for s in servers:
            s.stop()
        return rows

    want = continue_training(1)  # original sharding
    reshard_step(str(tmp_path), step=7, new_shards=3, prune_old=True)
    got = continue_training(3)  # resharded fleet, same next push
    np.testing.assert_array_equal(got, want)


@needs_native
def test_reshard_refuses_torn_snapshot(tmp_path):
    _trained_fleet_snapshot(tmp_path, 2)
    os.remove(tmp_path / "host_stores" / "7" / snapshot_filename("t", 1, 2))
    with pytest.raises(FileNotFoundError, match="torn"):
        reshard_step(str(tmp_path), step=7, new_shards=3)


@needs_native
def test_read_snapshot_roundtrip_format(tmp_path):
    """The python parser agrees with the C++ writer field-for-field."""
    _trained_fleet_snapshot(tmp_path, 1)
    path = tmp_path / "host_stores" / "7" / snapshot_filename("t", 0, 1)
    header, ids, adam_t, rows = read_snapshot(str(path))
    assert header["dim"] == IO.dim
    assert header["stride"] >= IO.dim  # row + adagrad accumulator slots
    assert ids.size == rows.shape[0] == adam_t.size
    assert np.unique(ids).size == ids.size  # one record per id


@needs_native
def test_reshard_refuses_mixed_shardings(tmp_path):
    """Without --prune-old the old sharding's files remain next to the new
    ones; a subsequent reshard must refuse the ambiguity rather than mix
    fleet sizes and silently drop rows."""
    _trained_fleet_snapshot(tmp_path, 2)
    reshard_step(str(tmp_path), step=7, new_shards=4)  # no prune
    with pytest.raises(ValueError, match="MULTIPLE fleet sizes"):
        reshard_step(str(tmp_path), step=7, new_shards=3)
