"""Hierarchical (dp, ep) mesh: data parallelism over the outer DCN-striding
axis, embedding tables sharded over the inner ICI axis (parallel/mesh.py).

The correctness bar: every observable — losses, trained tables, eval
metrics, predictions — must match the flat 1-D mesh exactly (same devices,
same seed, same batches); the hierarchy only changes WHICH collectives move
the data (grad psum over dp+ep, embedding all-to-all over ep alone).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.parallel.trainer import Trainer


def _deepfm(**over):
    kw = dict(
        buckets_per_feature=64, embedding_dim=8, hidden=(16,),
        compute_dtype="float32",
    )
    kw.update(over)
    return load_model_spec("elasticdl_tpu.models", "deepfm.model_spec", **kw)


def _batch(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "dense": rng.rand(n, 13).astype(np.float32) * 100,
        "cat": rng.randint(0, 1 << 20, (n, 26)).astype(np.int64),
        "labels": rng.randint(0, 2, (n,)).astype(np.int32),
    }


def _train(trainer, steps=3):
    state = trainer.init_state(jax.random.key(0))
    losses = []
    for s in range(steps):
        state, m = trainer.train_step(state, trainer.shard_batch(_batch(seed=s)))
        losses.append(float(m["loss"]))
    return losses, state


def test_mesh_shapes(devices):
    m = create_mesh(devices, dcn_parallelism=2)
    assert m.axis_names == ("dp", "ep")
    assert dict(m.shape) == {"dp": 2, "ep": 4}
    with pytest.raises(ValueError, match="does not divide"):
        create_mesh(devices[:6], dcn_parallelism=4)
    assert create_mesh(devices).axis_names == ("dp",)


def test_ps_training_matches_flat_mesh(devices):
    """Sharded-table (PS strategy) training on 2x4 and 4x2 meshes tracks the
    flat 8-device mesh loss-for-loss, and the trained table agrees."""
    spec = _deepfm()
    cfg = JobConfig(
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        embedding_lookup_impl="ragged_emulated",
    )
    flat_losses, flat_state = _train(Trainer(spec, cfg, create_mesh(devices)))
    for dcn in (2, 4):
        mesh = create_mesh(devices, dcn_parallelism=dcn)
        losses, state = _train(Trainer(spec, cfg, mesh))
        np.testing.assert_allclose(losses, flat_losses, rtol=1e-5)
        np.testing.assert_allclose(
            jax.device_get(state.params["fm_table"]),
            jax.device_get(flat_state.params["fm_table"]),
            rtol=1e-5, atol=1e-7,
        )


def test_table_sharded_over_inner_axis_only(devices):
    """The table's sharding names ONLY the ep axis — the dp axis never
    carries embedding traffic (each dp replica holds the same rows)."""
    spec = _deepfm()
    cfg = JobConfig(
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        embedding_lookup_impl="ragged_emulated",
    )
    trainer = Trainer(spec, cfg, create_mesh(devices, dcn_parallelism=2))
    state = trainer.init_state(jax.random.key(0))
    table_spec = state.params["fm_table"].sharding.spec
    assert tuple(table_spec) == ("ep",)
    assert trainer.ctx.axis_name == "ep"
    # auto would resolve against the EP axis size (4), not the mesh size (8).
    from elasticdl_tpu.ops.embedding import resolve_impl

    assert resolve_impl("auto", "tpu", axis_size=4) == "ragged"


def test_allreduce_strategy_on_hierarchical_mesh(devices):
    """AllReduce (no sharded tables): grads psum over BOTH axes — mnist
    trains to the same losses as the flat mesh."""
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )
    cfg = JobConfig(distribution_strategy=DistributionStrategy.ALLREDUCE)
    rng = np.random.RandomState(0)
    batch = {
        "images": rng.rand(16, 28, 28, 1).astype(np.float32),
        "labels": rng.randint(0, 10, (16,)).astype(np.int32),
    }

    def run(mesh):
        tr = Trainer(spec, cfg, mesh)
        st = tr.init_state(jax.random.key(0))
        out = []
        for _ in range(3):
            st, m = tr.train_step(st, tr.shard_batch(dict(batch)))
            out.append(float(m["loss"]))
        return out

    np.testing.assert_allclose(
        run(create_mesh(devices, dcn_parallelism=2)),
        run(create_mesh(devices)),
        rtol=1e-5,
    )


def test_eval_and_predict_match_flat(devices):
    spec = _deepfm()
    cfg = JobConfig(
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        embedding_lookup_impl="ragged_emulated",
    )
    batch = _batch()
    flat = Trainer(spec, cfg, create_mesh(devices))
    hier = Trainer(spec, cfg, create_mesh(devices, dcn_parallelism=2))
    fs = flat.init_state(jax.random.key(0))
    hs = hier.init_state(jax.random.key(0))
    from elasticdl_tpu.common.metrics import finalize_metrics

    fm = finalize_metrics(
        {k: np.asarray(v) for k, v in
         flat.eval_step(fs, flat.shard_batch(dict(batch))).items()}
    )
    hm = finalize_metrics(
        {k: np.asarray(v) for k, v in
         hier.eval_step(hs, hier.shard_batch(dict(batch))).items()}
    )
    assert fm.keys() == hm.keys()
    for k in fm:
        np.testing.assert_allclose(hm[k], fm[k], rtol=1e-5)
    fp = jax.device_get(flat.predict_step(fs, flat.shard_batch(dict(batch))))
    hp = jax.device_get(hier.predict_step(hs, hier.shard_batch(dict(batch))))
    np.testing.assert_allclose(hp, fp, rtol=1e-5)


def test_masked_eval_tail_exact_on_hierarchical(devices):
    """The exact-tail eval contract (psum-weighted masked means) holds over
    2-D meshes: metrics over a wrap-padded batch with __mask__ equal the
    unpadded single-device values."""
    spec = _deepfm()
    cfg = JobConfig(
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        embedding_lookup_impl="ragged_emulated",
    )
    real = _batch(n=10)
    padded = {k: np.concatenate([v, v[: 16 - 10]]) for k, v in real.items()}
    padded["__mask__"] = np.concatenate(
        [np.ones(10, np.float32), np.zeros(6, np.float32)]
    )
    hier = Trainer(spec, cfg, create_mesh(devices, dcn_parallelism=2))
    hs = hier.init_state(jax.random.key(0))
    from elasticdl_tpu.common.metrics import finalize_metrics

    got = finalize_metrics(
        {k: np.asarray(v) for k, v in
         hier.eval_step(hs, hier.shard_batch(padded)).items()}
    )
    # Ground truth: unsharded forward over the REAL rows only.
    params = jax.device_get(hs).params
    out = spec.apply(params, real, train=False)
    want = finalize_metrics(
        {k: np.asarray(v) for k, v in
         spec.metrics(jnp.asarray(out), real).items()}
    )
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-6)


def test_host_tier_on_hierarchical_mesh(devices):
    """Host-tier pull/push works over a 2-D mesh (host grads come back
    sharded over (dp, ep) jointly); loss matches the flat-mesh host-tier
    run."""
    pytest.importorskip("elasticdl_tpu.ps.host_store")
    from elasticdl_tpu.ps.host_store import native_lib_available

    if not native_lib_available():
        pytest.skip("native lib unavailable")
    spec = _deepfm(host_tier=True)
    assert spec.host_io
    cfg = JobConfig(distribution_strategy=DistributionStrategy.PARAMETER_SERVER)

    def run(mesh):
        tr = Trainer(spec, cfg, mesh)
        st = tr.init_state(jax.random.key(0))
        out = []
        for s in range(3):
            st, m = tr.run_train_step(st, _batch(seed=s))
            out.append(float(m["loss"]))
        return out

    np.testing.assert_allclose(
        run(create_mesh(devices, dcn_parallelism=2)),
        run(create_mesh(devices)),
        rtol=1e-5,
    )


def test_hierarchical_sequence_parallelism(devices):
    """SP on a (dp, ep) mesh: examples shard across the outer axis, the
    sequence (and ring attention) across the inner ICI axis.  Losses and a
    train step match the flat 1-D sequence-parallel mesh; predictions come
    back with the full global shape and match too."""
    spec = load_model_spec(
        "elasticdl_tpu.models", "transformer_lm.model_spec",
        vocab=128, dim=32, n_layers=2, n_heads=2, max_seq=64, seq_len=64,
        compute_dtype="float32",
    )
    assert spec.batch_shard_dim == 1
    rng = np.random.RandomState(0)
    toks = rng.randint(0, 128, (4, 65)).astype(np.int32)
    batch = {"tokens": toks[:, :-1], "labels": toks[:, 1:]}
    cfg = JobConfig(distribution_strategy=DistributionStrategy.ALLREDUCE)

    def run(mesh):
        tr = Trainer(spec, cfg, mesh)
        st = tr.init_state(jax.random.key(0))
        st, m = tr.train_step(st, tr.shard_batch(dict(batch)))
        pred = np.asarray(tr.predict_step(st, tr.shard_batch(dict(batch))))
        return float(m["loss"]), pred

    flat_loss, flat_pred = run(create_mesh(devices))
    hier_loss, hier_pred = run(create_mesh(devices, dcn_parallelism=2))
    np.testing.assert_allclose(hier_loss, flat_loss, rtol=1e-5)
    assert hier_pred.shape == (4, 64, 128)
    np.testing.assert_allclose(hier_pred, flat_pred, rtol=1e-4, atol=1e-5)

    # Per-example (mask-shaped) leaves follow the example dim's dp sharding
    # on hierarchical meshes; they replicate on 1-D SP meshes as before.
    from jax.sharding import PartitionSpec as P

    hier_tr = Trainer(spec, cfg, create_mesh(devices, dcn_parallelism=2))
    flat_tr = Trainer(spec, cfg, create_mesh(devices))
    mask = np.ones((4,), np.float32)
    assert hier_tr._batch_spec_for(mask) == P(("dp",))
    assert flat_tr._batch_spec_for(mask) == P()

    # Sequence not divisible by the INNER axis (4) fails loud; batch not
    # divisible by the outer axis too.
    tr = Trainer(spec, cfg, create_mesh(devices, dcn_parallelism=2))
    bad_seq = {"tokens": np.zeros((4, 62), np.int32),
               "labels": np.zeros((4, 62), np.int32)}
    with pytest.raises(ValueError, match="dimension 1"):
        tr.shard_batch(bad_seq)
    bad_b = {"tokens": np.zeros((3, 64), np.int32),
             "labels": np.zeros((3, 64), np.int32)}
    with pytest.raises(ValueError, match="dimension 0"):
        tr.shard_batch(bad_b)
