"""Sharded embedding lookup: parity with a plain gather, and gradient
correctness (incl. duplicate-id accumulation) — the TPU-native analogue of the
reference's embedding-layer-vs-fake-PS unit tests (SURVEY.md §4).

Tables are lane-packed [P, pack*dim] (pack = 128//dim logical rows per
physical row — ops/embedding.py module docstring); a plain [V, dim] table is
the pack == 1 case.  Tests cover both, since models use pack > 1 layouts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.ops.embedding import (
    ParallelContext,
    embedding_lookup,
    gather_rows,
    pack_table,
    pad_vocab,
    row_pack,
    table_shape,
    unpack_table,
)
from elasticdl_tpu.parallel.mesh import create_mesh

from elasticdl_tpu.common.jax_compat import shard_map

VOCAB = 64  # divisible by 8 so a [V, D] table div-shards cleanly
DIM = 16

# Both collective lookup routes.  "ragged_emulated" runs the real ragged
# routing/offset/unsort code with a dense emulation of the ragged-all-to-all
# collective (XLA:CPU has no ragged-all-to-all HLO; on TPU "auto" resolves to
# the real op through the identical code path).
IMPLS = ("dense", "ragged_emulated")

# Table layouts: plain [V, D] (pack=1: dim passed = width) and lane-packed
# [V/pack, pack*D] (pack=8 for DIM=16).  Both must behave identically.
LAYOUTS = ("plain", "packed")


def _table(rng):
    return jax.random.normal(rng, (VOCAB, DIM), jnp.float32)


def _layout(table2d, layout):
    """(table_array, lookup_dim) for a layout.  'packed' packs WITHOUT vocab
    padding (VOCAB already divides the mesh) so shard math stays exact."""
    if layout == "plain":
        return table2d, DIM
    pack = row_pack(DIM)
    return table2d.reshape(table2d.shape[0] // pack, pack * DIM), DIM


def _sharded_fn(mesh, impl="dense"):
    # Layout needs no parameter: embedding_lookup derives pack/stride from
    # the table array's width and dim=DIM, for plain and packed alike.
    axis = mesh.axis_names[0]
    ctx = ParallelContext(
        axis_name=axis, sharded_embeddings=True, embedding_impl=impl
    )
    return shard_map(
        lambda t, i: embedding_lookup(t, i, ctx, dim=DIM),
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )


def test_pad_vocab_and_shapes():
    # dim 128+ -> pack 1, physical rows = padded vocab, multiple of 256.
    assert pad_vocab(1, 128) == 256
    assert pad_vocab(256, 128) == 256
    assert pad_vocab(257, 128) == 512
    # dim 8 -> pack 16 -> vocab pads to 16*256=4096 logical rows.
    assert row_pack(8) == 16
    assert pad_vocab(1, 8) == 4096
    assert table_shape(1, 8) == (256, 128)
    # Criteo fused table: 26*65536 logical rows, dim 8.
    assert table_shape(26 * 65536, 8) == (26 * 65536 // 16, 128)
    # dim 1 -> pack 128.
    assert table_shape(1000, 1) == (256, 128)
    # dim that isn't a power of two: rows pad to the next-pow2 stride so the
    # physical width stays exactly 128 (misaligned widths gather ~3x slower).
    assert row_pack(48) == 2  # stride 64
    assert table_shape(513, 48) == (512, 128)  # 513 logical -> 1024 padded
    assert row_pack(9) == 8  # stride 16 (the DeepFM folded emb+linear table)
    assert table_shape(26 * 65536, 9) == (26 * 65536 // 8, 128)
    # dim > 128 pads to the next multiple of 128, pack 1.
    assert table_shape(300, 200) == (512, 256)


def test_pack_unpack_roundtrip():
    table = _table(jax.random.key(0))
    packed = pack_table(table, DIM)
    assert packed.shape == table_shape(VOCAB, DIM)
    # Rows survive, padding rows are zero.
    logical = unpack_table(packed, DIM)
    np.testing.assert_array_equal(np.asarray(logical[:VOCAB]), np.asarray(table))
    assert not np.asarray(logical[VOCAB:]).any()
    # Flat input packs identically.
    packed_flat = pack_table(table.reshape(-1), DIM)
    np.testing.assert_array_equal(np.asarray(packed_flat), np.asarray(packed))
    with pytest.raises(ValueError, match="multiple"):
        pack_table(jnp.zeros((65,)), DIM)


def test_packed_lookup_matches_plain(devices):
    """Lane-packed storage must agree with the plain [V, D] path, fwd and grad
    (including duplicate-id accumulation)."""
    table = _table(jax.random.key(0))
    packed, _ = _layout(table, "packed")
    ids = jnp.array([[3, 3], [0, 63], [17, 3]], jnp.int32)
    ctx = ParallelContext()
    out_plain = embedding_lookup(table, ids, ctx)
    out_packed = embedding_lookup(packed, ids, ctx, dim=DIM)
    np.testing.assert_allclose(
        np.asarray(out_packed), np.asarray(out_plain), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(gather_rows(packed, ids, DIM)), np.asarray(out_plain),
        rtol=1e-6,
    )

    cot = jax.random.normal(jax.random.key(2), out_plain.shape)
    g_plain = jax.grad(
        lambda t: jnp.sum(embedding_lookup(t, ids, ctx) * cot)
    )(table)
    g_packed = jax.grad(
        lambda t: jnp.sum(embedding_lookup(t, ids, ctx, dim=DIM) * cot)
    )(packed)
    np.testing.assert_allclose(
        np.asarray(g_packed).reshape(-1, DIM),
        np.asarray(g_plain),
        rtol=1e-5,
    )


def test_stride_padded_lookup_matches_plain():
    """Non-power-of-two dim (9, the DeepFM folded table): rows live at
    stride 16 with dead lanes; lookup and grad must match the plain table."""
    dim = 9
    table = jax.random.normal(jax.random.key(0), (40, dim), jnp.float32)
    packed = pack_table(table, dim)
    assert packed.shape == table_shape(40, dim)
    # dup + 2 OOV (3000 is past the PADDED vocab of 2048; 40..2047 are valid
    # zero padding rows by the module contract, not OOV)
    ids = jnp.array([0, 7, 39, 7, 3000, -1], jnp.int32)
    out = np.asarray(gather_rows(packed, ids, dim))
    exp = np.asarray(table)
    for i, idx in enumerate([0, 7, 39, 7]):
        np.testing.assert_allclose(out[i], exp[idx], rtol=1e-6)
    assert np.isnan(out[4]).all() and np.isnan(out[5]).all()

    cot = jax.random.normal(jax.random.key(1), (6, dim))
    g_packed = jax.grad(
        lambda t: jnp.sum(jnp.where(jnp.isnan(gather_rows(t, ids, dim)), 0.0,
                                    gather_rows(t, ids, dim) * cot))
    )(packed)
    good = [0, 7, 39, 7]
    g_exp = jax.grad(
        lambda t: jnp.sum(jnp.take(t, jnp.array(good), axis=0) * cot[:4])
    )(table)
    np.testing.assert_allclose(
        np.asarray(unpack_table(g_packed, dim))[:40], np.asarray(g_exp),
        rtol=1e-5, atol=1e-6,
    )


def test_pad_embedding_tables_undersized_leaf():
    """A user table built for the RAW vocab (fewer rows than the declared
    padded vocab) zero-pads up to the declared shape; an oversized or
    wrong-width leaf raises."""
    from elasticdl_tpu.models.spec import EmbeddingTableSpec
    from elasticdl_tpu.parallel.trainer import pad_embedding_tables

    spec = [EmbeddingTableSpec(path=("t",), vocab_size=5000, dim=16)]
    leaf = jnp.ones((1000, 16), jnp.float32)
    out = pad_embedding_tables({"t": leaf}, spec)["t"]
    assert out.shape == table_shape(5000, 16)
    logical = unpack_table(out, 16)
    np.testing.assert_array_equal(np.asarray(logical[:1000]), np.asarray(leaf))
    assert not np.asarray(logical[1000:]).any()

    with pytest.raises(ValueError, match="incompatible"):
        pad_embedding_tables({"t": jnp.ones((9000, 16))}, spec)


def test_lookup_validation():
    ctx = ParallelContext()
    with pytest.raises(ValueError, match="pack_table"):
        embedding_lookup(jnp.zeros((64,)), jnp.zeros((2,), jnp.int32), ctx)
    with pytest.raises(ValueError, match="stride"):
        embedding_lookup(
            jnp.zeros((64, 6)), jnp.zeros((2,), jnp.int32), ctx, dim=3
        )


def test_oov_is_nan_local():
    """Single-device fail-loud OOV for both layouts, both id signs."""
    table = _table(jax.random.key(0))
    for layout in LAYOUTS:
        arr, dim = _layout(table, layout)
        ids = jnp.array([0, -1, VOCAB - 1, VOCAB, 2**30, -(2**30)], jnp.int32)
        out = np.asarray(gather_rows(arr, ids, dim))
        np.testing.assert_allclose(out[0], np.asarray(table)[0], rtol=1e-6)
        np.testing.assert_allclose(
            out[2], np.asarray(table)[VOCAB - 1], rtol=1e-6
        )
        for bad in (1, 3, 4, 5):
            assert np.isnan(out[bad]).all(), (layout, bad)


@pytest.mark.parametrize("layout", LAYOUTS)
@pytest.mark.parametrize("impl", IMPLS)
@pytest.mark.parametrize("n_dev", [1, 4, 8])
def test_sharded_lookup_matches_gather(devices, n_dev, impl, layout):
    mesh = create_mesh(devices, num_devices=n_dev)
    table = _table(jax.random.key(0))
    arr, dim = _layout(table, layout)
    ids = jax.random.randint(jax.random.key(1), (32,), 0, VOCAB)

    expected = jnp.take(table, ids, axis=0)
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(mesh.axis_names[0])))
    out = jax.jit(_sharded_fn(mesh, impl))(sh(arr), sh(ids))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)


@pytest.mark.parametrize("layout", LAYOUTS)
@pytest.mark.parametrize("impl", IMPLS)
def test_sharded_lookup_skewed_ids(devices, impl, layout):
    """Worst-case routing skew: every device's ids all live on ONE shard (the
    ragged route's send sizes are maximally unbalanced)."""
    mesh = create_mesh(devices)
    table = _table(jax.random.key(0))
    arr, dim = _layout(table, layout)
    rows_per_shard = VOCAB // 8
    # All 32 ids hit shard 5's row range.
    ids = jax.random.randint(
        jax.random.key(3), (32,), 5 * rows_per_shard, 6 * rows_per_shard
    )
    expected = jnp.take(table, ids, axis=0)
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(mesh.axis_names[0])))
    out = jax.jit(_sharded_fn(mesh, impl))(sh(arr), sh(ids))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)


@pytest.mark.parametrize("impl", IMPLS)
def test_sharded_lookup_2d_ids(devices, impl):
    """ids shaped [batch, n_features] — the tabular-model case."""
    mesh = create_mesh(devices)
    table = _table(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (16, 5), 0, VOCAB)

    expected = jnp.take(table, ids, axis=0)
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(mesh.axis_names[0])))
    out = jax.jit(_sharded_fn(mesh, impl))(sh(table), sh(ids))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)


@pytest.mark.parametrize("layout", LAYOUTS)
@pytest.mark.parametrize("impl", IMPLS)
def test_sharded_lookup_gradient_accumulates_duplicates(devices, impl, layout):
    """d(loss)/d(table) must scatter-ADD cotangents for duplicate ids — the
    reference's IndexedSlices semantics on the PS side."""
    mesh = create_mesh(devices)
    axis = mesh.axis_names[0]
    table = _table(jax.random.key(0))
    arr, dim = _layout(table, layout)
    # Every device looks up id 3 (heavy duplication across the mesh) plus a
    # unique id, so the grad row for 3 accumulates 8 contributions.
    ids = jnp.array([3, 3, 3, 3, 3, 3, 3, 3, 0, 1, 2, 4, 5, 6, 7, 8], jnp.int32)
    cot = jax.random.normal(jax.random.key(2), (ids.shape[0], DIM))

    def ref_loss(t):
        return jnp.sum(jnp.take(t, ids, axis=0) * cot)

    expected_grad = np.asarray(jax.grad(ref_loss)(table))

    ctx = ParallelContext(
        axis_name=axis, sharded_embeddings=True, embedding_impl=impl
    )

    def local_loss(t, i, c):
        # Per-device scalar, NOT psum'd: under AD each device's cotangent is 1,
        # so the collective transposes deliver d(sum_i loss_i)/d(table) into the
        # row shards.  (psum inside the grad would double-count under
        # check_vma=False, whose conservative psum transpose is psum.)
        vec = embedding_lookup(t, i, ctx, dim=DIM)
        return jnp.sum(vec * c)

    mapped = shard_map(
        jax.grad(local_loss),
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(axis)))
    grad = np.asarray(jax.jit(mapped)(sh(arr), sh(ids), sh(cot)))
    np.testing.assert_allclose(
        grad.reshape(-1, DIM), expected_grad, rtol=1e-5
    )


@pytest.mark.parametrize("layout", LAYOUTS)
@pytest.mark.parametrize("impl", IMPLS)
def test_sharded_lookup_oov_is_nan(devices, impl, layout):
    """Fail-loud OOV: ids outside the padded global vocab come back as NaN
    rows in SHARDED mode too (VERDICT r1 'loud OOV'), never zeros or a
    silently wrong row; in-range rows are unaffected."""
    mesh = create_mesh(devices)
    table = _table(jax.random.key(0))
    arr, dim = _layout(table, layout)
    ids = np.random.default_rng(0).integers(0, VOCAB, size=(32,)).astype(np.int32)
    bad_slots = [0, 5, 17, 31]
    ids[bad_slots[0]] = VOCAB * 10  # far out of range
    ids[bad_slots[1]] = -3
    ids[bad_slots[2]] = VOCAB  # first row past the end
    ids[bad_slots[3]] = 2**30  # huge junk id
    ids = jnp.asarray(ids)

    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(mesh.axis_names[0])))
    out = np.asarray(jax.jit(_sharded_fn(mesh, impl))(sh(arr), sh(ids)))
    for i in range(32):
        if i in bad_slots:
            assert np.isnan(out[i]).all(), f"row {i} (junk id) must be NaN"
        else:
            np.testing.assert_allclose(
                out[i], np.asarray(table)[int(ids[i])], rtol=1e-6
            )


@pytest.mark.parametrize("layout", LAYOUTS)
@pytest.mark.parametrize("impl", IMPLS)
def test_sharded_lookup_oov_gradient_dropped(devices, impl, layout):
    """Junk-id cotangents are dropped, not scattered into a wrong row: the
    grad with junk ids present equals the grad with them excluded."""
    mesh = create_mesh(devices)
    axis = mesh.axis_names[0]
    table = _table(jax.random.key(0))
    arr, dim = _layout(table, layout)
    ids = jnp.array(
        [3, -7, 3, VOCAB * 4, 9, 2**30, 1, 0] + list(range(8)), jnp.int32
    )
    cot = jax.random.normal(jax.random.key(2), (ids.shape[0], DIM))

    good = np.asarray(ids) >= 0
    good &= np.asarray(ids) < VOCAB
    expected = np.asarray(
        jax.grad(
            lambda t: jnp.sum(
                jnp.take(t, jnp.asarray(np.asarray(ids)[good]), axis=0)
                * jnp.asarray(np.asarray(cot)[good])
            )
        )(table)
    )

    ctx = ParallelContext(
        axis_name=axis, sharded_embeddings=True, embedding_impl=impl
    )

    def local_loss(t, i, c):
        vec = embedding_lookup(t, i, ctx, dim=DIM)
        return jnp.sum(jnp.where(jnp.isnan(vec), 0.0, vec * c))

    mapped = shard_map(
        jax.grad(local_loss),
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(axis)))
    grad = np.asarray(jax.jit(mapped)(sh(arr), sh(ids), sh(cot)))
    np.testing.assert_allclose(
        grad.reshape(-1, DIM), expected, rtol=1e-5, atol=1e-6
    )


def test_resolve_impl_mesh_size_aware():
    """auto at axis_size 1 is a local gather (dense n=1 short-circuit), never
    the ragged machinery — VERDICT r2 Weak #1.  Explicit impls pass through."""
    from elasticdl_tpu.ops.embedding import resolve_impl

    assert resolve_impl("auto", "tpu", axis_size=1) == "dense"
    assert resolve_impl("auto", "tpu", axis_size=8) == "ragged"
    assert resolve_impl("auto", "cpu", axis_size=8) == "dense"
    assert resolve_impl("ragged", "tpu", axis_size=1) == "ragged"
    assert resolve_impl("ragged_emulated", "cpu", axis_size=1) == "ragged_emulated"
    with pytest.raises(ValueError, match="unknown"):
        resolve_impl("bogus")


def test_lookup_impls_match_config():
    """config.py inlines the impl tuple (to stay jax-free); keep in sync."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.ops.embedding import LOOKUP_IMPLS

    for impl in LOOKUP_IMPLS:
        JobConfig(embedding_lookup_impl=impl).validate()
    with pytest.raises(ValueError, match="embedding_lookup_impl"):
        JobConfig(embedding_lookup_impl="bogus").validate()
