"""Sharded embedding lookup: parity with a plain gather, and gradient
correctness (incl. duplicate-id accumulation) — the TPU-native analogue of the
reference's embedding-layer-vs-fake-PS unit tests (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.ops.embedding import (
    ParallelContext,
    embedding_lookup,
    pad_vocab,
)
from elasticdl_tpu.parallel.mesh import create_mesh

try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

VOCAB = 64  # divisible by 8 so a [V, D] table div-shards cleanly
DIM = 16


def _table(rng):
    return jax.random.normal(rng, (VOCAB, DIM), jnp.float32)


def _sharded_fn(mesh, fn):
    axis = mesh.axis_names[0]
    ctx = ParallelContext(axis_name=axis, sharded_embeddings=True)
    return shard_map(
        lambda t, i: fn(t, i, ctx),
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )


def test_pad_vocab():
    assert pad_vocab(1) == 256
    assert pad_vocab(256) == 256
    assert pad_vocab(257) == 512


def test_flat_lookup_matches_2d(devices):
    """Flat [V*D] storage must agree with the 2-D [V, D] path, fwd and grad
    (including duplicate-id accumulation)."""
    from elasticdl_tpu.ops.embedding import gather_rows

    table = _table(jax.random.key(0))
    flat = table.reshape(-1)
    ids = jnp.array([[3, 3], [0, 63], [17, 3]], jnp.int32)
    ctx = ParallelContext()
    out2 = embedding_lookup(table, ids, ctx)
    out1 = embedding_lookup(flat, ids, ctx, dim=DIM)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(gather_rows(flat, ids, DIM)), np.asarray(out2), rtol=1e-6
    )

    cot = jax.random.normal(jax.random.key(2), out2.shape)
    g2 = jax.grad(lambda t: jnp.sum(embedding_lookup(t, ids, ctx) * cot))(table)
    g1 = jax.grad(
        lambda t: jnp.sum(embedding_lookup(t, ids, ctx, dim=DIM) * cot)
    )(flat)
    np.testing.assert_allclose(
        np.asarray(g1), np.asarray(g2).reshape(-1), rtol=1e-5
    )


def test_flat_table_int32_guard():
    from elasticdl_tpu.ops.embedding import flat_table_size

    assert flat_table_size(1000, 8) == 1024 * 8
    with pytest.raises(ValueError, match="int32"):
        flat_table_size(300_000_000, 8)


def test_flat_lookup_dim_validation():
    ctx = ParallelContext()
    with pytest.raises(ValueError, match="explicit dim"):
        embedding_lookup(jnp.zeros((64,)), jnp.zeros((2,), jnp.int32), ctx)
    with pytest.raises(ValueError, match="dim"):
        embedding_lookup(
            jnp.zeros((64, 4)), jnp.zeros((2,), jnp.int32), ctx, dim=8
        )


@pytest.mark.parametrize("n_dev", [1, 4, 8])
def test_sharded_flat_lookup_matches_gather(devices, n_dev):
    mesh = create_mesh(devices, num_devices=n_dev)
    axis = mesh.axis_names[0]
    table = _table(jax.random.key(0))
    flat = table.reshape(-1)
    ids = jax.random.randint(jax.random.key(1), (32,), 0, VOCAB)
    expected = jnp.take(table, ids, axis=0)

    ctx = ParallelContext(axis_name=axis, sharded_embeddings=True)
    mapped = shard_map(
        lambda t, i: embedding_lookup(t, i, ctx, dim=DIM),
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(axis)))
    out = jax.jit(mapped)(sh(flat), sh(ids))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)


def test_sharded_flat_gradient_duplicates(devices):
    mesh = create_mesh(devices)
    axis = mesh.axis_names[0]
    table = _table(jax.random.key(0))
    flat = table.reshape(-1)
    ids = jnp.array([3, 3, 3, 3, 3, 3, 3, 3, 0, 1, 2, 4, 5, 6, 7, 8], jnp.int32)
    cot = jax.random.normal(jax.random.key(2), (ids.shape[0], DIM))

    expected = jax.grad(
        lambda t: jnp.sum(jnp.take(t, ids, axis=0) * cot)
    )(table).reshape(-1)

    ctx = ParallelContext(axis_name=axis, sharded_embeddings=True)
    mapped = shard_map(
        jax.grad(
            lambda t, i, c: jnp.sum(embedding_lookup(t, i, ctx, dim=DIM) * c)
        ),
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(axis)))
    grad = jax.jit(mapped)(sh(flat), sh(ids), sh(cot))
    np.testing.assert_allclose(np.asarray(grad), np.asarray(expected), rtol=1e-5)


@pytest.mark.parametrize("n_dev", [1, 4, 8])
def test_sharded_lookup_matches_gather(devices, n_dev):
    mesh = create_mesh(devices, num_devices=n_dev)
    table = _table(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (32,), 0, VOCAB)

    expected = jnp.take(table, ids, axis=0)

    table_s = jax.device_put(table, NamedSharding(mesh, P(mesh.axis_names[0])))
    ids_s = jax.device_put(ids, NamedSharding(mesh, P(mesh.axis_names[0])))
    out = jax.jit(_sharded_fn(mesh, embedding_lookup))(table_s, ids_s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)


def test_sharded_lookup_2d_ids(devices):
    """ids shaped [batch, n_features] — the tabular-model case."""
    mesh = create_mesh(devices)
    table = _table(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (16, 5), 0, VOCAB)

    expected = jnp.take(table, ids, axis=0)
    table_s = jax.device_put(table, NamedSharding(mesh, P(mesh.axis_names[0])))
    ids_s = jax.device_put(ids, NamedSharding(mesh, P(mesh.axis_names[0])))
    out = jax.jit(_sharded_fn(mesh, embedding_lookup))(table_s, ids_s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)


def test_sharded_lookup_gradient_accumulates_duplicates(devices):
    """d(loss)/d(table) must scatter-ADD cotangents for duplicate ids — the
    reference's IndexedSlices semantics on the PS side."""
    mesh = create_mesh(devices)
    axis = mesh.axis_names[0]
    table = _table(jax.random.key(0))
    # Every device looks up id 3 (heavy duplication across the mesh) plus a
    # unique id, so the grad row for 3 accumulates 8 contributions.
    ids = jnp.array([3, 3, 3, 3, 3, 3, 3, 3, 0, 1, 2, 4, 5, 6, 7, 8], jnp.int32)
    cot = jax.random.normal(jax.random.key(2), (ids.shape[0], DIM))

    def ref_loss(t):
        return jnp.sum(jnp.take(t, ids, axis=0) * cot)

    expected_grad = jax.grad(ref_loss)(table)

    ctx = ParallelContext(axis_name=axis, sharded_embeddings=True)

    def local_loss(t, i, c):
        # Per-device scalar, NOT psum'd: under AD each device's cotangent is 1,
        # so the collective transposes deliver d(sum_i loss_i)/d(table) into the
        # row shards.  (psum inside the grad would double-count under
        # check_vma=False, whose conservative psum transpose is psum.)
        vec = embedding_lookup(t, i, ctx)
        return jnp.sum(vec * c)

    mapped = shard_map(
        jax.grad(local_loss),
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(axis)))
    grad = jax.jit(mapped)(sh(table), sh(ids), sh(cot))
    np.testing.assert_allclose(np.asarray(grad), np.asarray(expected_grad), rtol=1e-5)
