"""Sharded embedding lookup: parity with a plain gather, and gradient
correctness (incl. duplicate-id accumulation) — the TPU-native analogue of the
reference's embedding-layer-vs-fake-PS unit tests (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.ops.embedding import (
    ParallelContext,
    embedding_lookup,
    pad_vocab,
)
from elasticdl_tpu.parallel.mesh import create_mesh

try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

VOCAB = 64  # divisible by 8 so a [V, D] table div-shards cleanly
DIM = 16

# Both collective lookup routes.  "ragged_emulated" runs the real ragged
# routing/offset/unsort code with a dense emulation of the ragged-all-to-all
# collective (XLA:CPU has no ragged-all-to-all HLO; on TPU "auto" resolves to
# the real op through the identical code path).
IMPLS = ("dense", "ragged_emulated")


def _table(rng):
    return jax.random.normal(rng, (VOCAB, DIM), jnp.float32)


def _sharded_fn(mesh, fn, impl="dense"):
    axis = mesh.axis_names[0]
    ctx = ParallelContext(
        axis_name=axis, sharded_embeddings=True, embedding_impl=impl
    )
    return shard_map(
        lambda t, i: fn(t, i, ctx),
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )


def test_pad_vocab():
    assert pad_vocab(1) == 256
    assert pad_vocab(256) == 256
    assert pad_vocab(257) == 512


def test_flat_lookup_matches_2d(devices):
    """Flat [V*D] storage must agree with the 2-D [V, D] path, fwd and grad
    (including duplicate-id accumulation)."""
    from elasticdl_tpu.ops.embedding import gather_rows

    table = _table(jax.random.key(0))
    flat = table.reshape(-1)
    ids = jnp.array([[3, 3], [0, 63], [17, 3]], jnp.int32)
    ctx = ParallelContext()
    out2 = embedding_lookup(table, ids, ctx)
    out1 = embedding_lookup(flat, ids, ctx, dim=DIM)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(gather_rows(flat, ids, DIM)), np.asarray(out2), rtol=1e-6
    )

    cot = jax.random.normal(jax.random.key(2), out2.shape)
    g2 = jax.grad(lambda t: jnp.sum(embedding_lookup(t, ids, ctx) * cot))(table)
    g1 = jax.grad(
        lambda t: jnp.sum(embedding_lookup(t, ids, ctx, dim=DIM) * cot)
    )(flat)
    np.testing.assert_allclose(
        np.asarray(g1), np.asarray(g2).reshape(-1), rtol=1e-5
    )


def test_flat_table_int32_guard():
    from elasticdl_tpu.ops.embedding import flat_table_size

    assert flat_table_size(1000, 8) == 1024 * 8
    with pytest.raises(ValueError, match="int32"):
        flat_table_size(300_000_000, 8)


def test_flat_lookup_dim_validation():
    ctx = ParallelContext()
    with pytest.raises(ValueError, match="explicit dim"):
        embedding_lookup(jnp.zeros((64,)), jnp.zeros((2,), jnp.int32), ctx)
    with pytest.raises(ValueError, match="dim"):
        embedding_lookup(
            jnp.zeros((64, 4)), jnp.zeros((2,), jnp.int32), ctx, dim=8
        )


@pytest.mark.parametrize("impl", IMPLS)
@pytest.mark.parametrize("n_dev", [1, 4, 8])
def test_sharded_flat_lookup_matches_gather(devices, n_dev, impl):
    mesh = create_mesh(devices, num_devices=n_dev)
    axis = mesh.axis_names[0]
    table = _table(jax.random.key(0))
    flat = table.reshape(-1)
    ids = jax.random.randint(jax.random.key(1), (32,), 0, VOCAB)
    expected = jnp.take(table, ids, axis=0)

    ctx = ParallelContext(
        axis_name=axis, sharded_embeddings=True, embedding_impl=impl
    )
    mapped = shard_map(
        lambda t, i: embedding_lookup(t, i, ctx, dim=DIM),
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(axis)))
    out = jax.jit(mapped)(sh(flat), sh(ids))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)


@pytest.mark.parametrize("impl", IMPLS)
def test_sharded_flat_gradient_duplicates(devices, impl):
    mesh = create_mesh(devices)
    axis = mesh.axis_names[0]
    table = _table(jax.random.key(0))
    flat = table.reshape(-1)
    ids = jnp.array([3, 3, 3, 3, 3, 3, 3, 3, 0, 1, 2, 4, 5, 6, 7, 8], jnp.int32)
    cot = jax.random.normal(jax.random.key(2), (ids.shape[0], DIM))

    expected = jax.grad(
        lambda t: jnp.sum(jnp.take(t, ids, axis=0) * cot)
    )(table).reshape(-1)

    ctx = ParallelContext(
        axis_name=axis, sharded_embeddings=True, embedding_impl=impl
    )
    mapped = shard_map(
        jax.grad(
            lambda t, i, c: jnp.sum(embedding_lookup(t, i, ctx, dim=DIM) * c)
        ),
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(axis)))
    grad = jax.jit(mapped)(sh(flat), sh(ids), sh(cot))
    np.testing.assert_allclose(np.asarray(grad), np.asarray(expected), rtol=1e-5)


@pytest.mark.parametrize("impl", IMPLS)
@pytest.mark.parametrize("n_dev", [1, 4, 8])
def test_sharded_lookup_matches_gather(devices, n_dev, impl):
    mesh = create_mesh(devices, num_devices=n_dev)
    table = _table(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (32,), 0, VOCAB)

    expected = jnp.take(table, ids, axis=0)

    table_s = jax.device_put(table, NamedSharding(mesh, P(mesh.axis_names[0])))
    ids_s = jax.device_put(ids, NamedSharding(mesh, P(mesh.axis_names[0])))
    out = jax.jit(_sharded_fn(mesh, embedding_lookup, impl))(table_s, ids_s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)


@pytest.mark.parametrize("impl", IMPLS)
def test_sharded_lookup_skewed_ids(devices, impl):
    """Worst-case routing skew: every device's ids all live on ONE shard (the
    ragged route's send sizes are maximally unbalanced)."""
    mesh = create_mesh(devices)
    table = _table(jax.random.key(0))
    rows_per_shard = VOCAB // 8
    # All 32 ids hit shard 5's row range.
    ids = jax.random.randint(
        jax.random.key(3), (32,), 5 * rows_per_shard, 6 * rows_per_shard
    )
    expected = jnp.take(table, ids, axis=0)
    table_s = jax.device_put(table, NamedSharding(mesh, P(mesh.axis_names[0])))
    ids_s = jax.device_put(ids, NamedSharding(mesh, P(mesh.axis_names[0])))
    out = jax.jit(_sharded_fn(mesh, embedding_lookup, impl))(table_s, ids_s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)


@pytest.mark.parametrize("impl", IMPLS)
def test_sharded_lookup_2d_ids(devices, impl):
    """ids shaped [batch, n_features] — the tabular-model case."""
    mesh = create_mesh(devices)
    table = _table(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (16, 5), 0, VOCAB)

    expected = jnp.take(table, ids, axis=0)
    table_s = jax.device_put(table, NamedSharding(mesh, P(mesh.axis_names[0])))
    ids_s = jax.device_put(ids, NamedSharding(mesh, P(mesh.axis_names[0])))
    out = jax.jit(_sharded_fn(mesh, embedding_lookup, impl))(table_s, ids_s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)


@pytest.mark.parametrize("impl", IMPLS)
def test_sharded_lookup_gradient_accumulates_duplicates(devices, impl):
    """d(loss)/d(table) must scatter-ADD cotangents for duplicate ids — the
    reference's IndexedSlices semantics on the PS side."""
    mesh = create_mesh(devices)
    axis = mesh.axis_names[0]
    table = _table(jax.random.key(0))
    # Every device looks up id 3 (heavy duplication across the mesh) plus a
    # unique id, so the grad row for 3 accumulates 8 contributions.
    ids = jnp.array([3, 3, 3, 3, 3, 3, 3, 3, 0, 1, 2, 4, 5, 6, 7, 8], jnp.int32)
    cot = jax.random.normal(jax.random.key(2), (ids.shape[0], DIM))

    def ref_loss(t):
        return jnp.sum(jnp.take(t, ids, axis=0) * cot)

    expected_grad = jax.grad(ref_loss)(table)

    ctx = ParallelContext(
        axis_name=axis, sharded_embeddings=True, embedding_impl=impl
    )

    def local_loss(t, i, c):
        # Per-device scalar, NOT psum'd: under AD each device's cotangent is 1,
        # so the collective transposes deliver d(sum_i loss_i)/d(table) into the
        # row shards.  (psum inside the grad would double-count under
        # check_vma=False, whose conservative psum transpose is psum.)
        vec = embedding_lookup(t, i, ctx)
        return jnp.sum(vec * c)

    mapped = shard_map(
        jax.grad(local_loss),
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(axis)))
    grad = jax.jit(mapped)(sh(table), sh(ids), sh(cot))
    np.testing.assert_allclose(np.asarray(grad), np.asarray(expected_grad), rtol=1e-5)


@pytest.mark.parametrize("impl", IMPLS)
def test_sharded_lookup_oov_is_nan(devices, impl):
    """Fail-loud OOV: ids outside the padded global vocab come back as NaN
    rows in SHARDED mode too (VERDICT r1 'loud OOV'), never zeros or a
    silently wrong row; in-range rows are unaffected."""
    mesh = create_mesh(devices)
    table = _table(jax.random.key(0))
    ids = np.random.default_rng(0).integers(0, VOCAB, size=(32,)).astype(np.int32)
    bad_slots = [0, 5, 17, 31]
    ids[bad_slots[0]] = VOCAB * 10  # far out of range (also int32-overflow bait)
    ids[bad_slots[1]] = -3
    ids[bad_slots[2]] = VOCAB  # first row past the end
    ids[bad_slots[3]] = 2**30  # would overflow id*dim in int32
    ids = jnp.asarray(ids)

    table_s = jax.device_put(table, NamedSharding(mesh, P(mesh.axis_names[0])))
    ids_s = jax.device_put(ids, NamedSharding(mesh, P(mesh.axis_names[0])))
    out = np.asarray(
        jax.jit(_sharded_fn(mesh, embedding_lookup, impl))(table_s, ids_s)
    )
    for i in range(32):
        if i in bad_slots:
            assert np.isnan(out[i]).all(), f"row {i} (junk id) must be NaN"
        else:
            np.testing.assert_allclose(
                out[i], np.asarray(table)[int(ids[i])], rtol=1e-6
            )


@pytest.mark.parametrize("impl", IMPLS)
def test_sharded_lookup_oov_gradient_dropped(devices, impl):
    """Junk-id cotangents are dropped, not scattered into a wrong row: the
    grad with junk ids present equals the grad with them excluded."""
    mesh = create_mesh(devices)
    axis = mesh.axis_names[0]
    table = _table(jax.random.key(0))
    ids = jnp.array(
        [3, -7, 3, VOCAB * 4, 9, 2**30, 1, 0] + list(range(8)), jnp.int32
    )
    cot = jax.random.normal(jax.random.key(2), (ids.shape[0], DIM))

    good = np.asarray(ids) >= 0
    good &= np.asarray(ids) < VOCAB
    expected = jax.grad(
        lambda t: jnp.sum(
            jnp.take(t, jnp.asarray(np.asarray(ids)[good]), axis=0)
            * jnp.asarray(np.asarray(cot)[good])
        )
    )(table)

    ctx = ParallelContext(
        axis_name=axis, sharded_embeddings=True, embedding_impl=impl
    )

    def local_loss(t, i, c):
        vec = embedding_lookup(t, i, ctx)
        return jnp.sum(jnp.where(jnp.isnan(vec), 0.0, vec * c))

    mapped = shard_map(
        jax.grad(local_loss),
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, P(axis)))
    grad = jax.jit(mapped)(sh(table), sh(ids), sh(cot))
    np.testing.assert_allclose(
        np.asarray(grad), np.asarray(expected), rtol=1e-5, atol=1e-6
    )


def test_lookup_impls_match_config():
    """config.py inlines the impl tuple (to stay jax-free); keep in sync."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.ops.embedding import LOOKUP_IMPLS

    for impl in LOOKUP_IMPLS:
        JobConfig(embedding_lookup_impl=impl).validate()
    with pytest.raises(ValueError, match="embedding_lookup_impl"):
        JobConfig(embedding_lookup_impl="bogus").validate()
