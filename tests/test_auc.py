"""Streaming ROC AUC (the reference's headline Criteo/DeepFM eval metric):
score histograms flow through every aggregation layer — device psum, worker
minibatch sums, master cross-worker weighted means — and the scalar derived
at the end equals the AUC of the pooled predictions."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.common.metrics import (
    AUC_NEG,
    AUC_POS,
    auc_from_histograms,
    finalize_metrics,
)
from elasticdl_tpu.models.metrics import AUC_BINS, auc_histograms


def _exact_auc(scores, labels):
    """O(P*N) pairwise reference: wins + half-ties over all pos/neg pairs."""
    pos = scores[labels == 1]
    neg = scores[labels == 0]
    if len(pos) == 0 or len(neg) == 0:
        return 0.5
    wins = (pos[:, None] > neg[None, :]).sum()
    ties = (pos[:, None] == neg[None, :]).sum()
    return (wins + 0.5 * ties) / (len(pos) * len(neg))


def _quantize(scores, bins=AUC_BINS):
    """Snap scores to the histogram's bucket grid: histogram AUC is then
    EXACT, not just O(1/bins)-close."""
    return (np.clip((scores * bins).astype(int), 0, bins - 1) + 0.5) / bins


def test_histogram_auc_exact_on_grid():
    rng = np.random.RandomState(0)
    scores = _quantize(rng.rand(500))
    labels = (rng.rand(500) < 0.3).astype(np.int32)
    hists = auc_histograms(jnp.asarray(scores), jnp.asarray(labels))
    got = auc_from_histograms(np.asarray(hists[AUC_POS]), np.asarray(hists[AUC_NEG]))
    # "Exact" up to the f32 device-side normalization (counts/total in f32).
    np.testing.assert_allclose(got, _exact_auc(scores, labels), rtol=1e-6)


def test_histogram_auc_close_off_grid():
    rng = np.random.RandomState(1)
    # Separable-ish scores: positives skew high.
    labels = (rng.rand(2000) < 0.4).astype(np.int32)
    scores = np.clip(rng.rand(2000) * 0.6 + labels * 0.3, 0, 1)
    hists = auc_histograms(jnp.asarray(scores), jnp.asarray(labels))
    got = auc_from_histograms(np.asarray(hists[AUC_POS]), np.asarray(hists[AUC_NEG]))
    assert abs(got - _exact_auc(scores, labels)) < 2.0 / AUC_BINS


def test_masked_rows_excluded():
    scores = jnp.asarray([0.9, 0.1, 0.5, 0.5])
    labels = jnp.asarray([1, 0, 1, 0])
    mask = jnp.asarray([1.0, 1.0, 0.0, 0.0])
    hists = auc_histograms(scores, labels, mask)
    assert float(np.asarray(hists[AUC_POS]).sum() * 2) == pytest.approx(1.0)
    got = auc_from_histograms(np.asarray(hists[AUC_POS]), np.asarray(hists[AUC_NEG]))
    assert got == 1.0  # only the separable pair counts


def test_degenerate_sets_return_half():
    assert auc_from_histograms(np.zeros(8), np.ones(8)) == 0.5
    assert auc_from_histograms(np.ones(8), np.zeros(8)) == 0.5


def test_finalize_metrics_derives_and_strips():
    hists = auc_histograms(
        jnp.asarray(_quantize(np.array([0.9, 0.2]))), jnp.asarray([1, 0])
    )
    out = finalize_metrics({"loss": jnp.asarray(0.5), **hists})
    assert set(out) == {"loss", "auc"}
    assert out["auc"] == 1.0 and out["loss"] == 0.5


def test_weighted_mean_aggregation_is_exact():
    """The master's aggregation path: two disjoint shards' histogram MEANS,
    weight-averaged by example count, derive the pooled AUC exactly."""
    rng = np.random.RandomState(2)
    scores = _quantize(rng.rand(300))
    labels = (rng.rand(300) < 0.5).astype(np.int32)
    split = 120  # unequal shards
    parts = [(scores[:split], labels[:split]), (scores[split:], labels[split:])]
    agg_sums, agg_counts = {}, {}
    for s, l in parts:
        h = auc_histograms(jnp.asarray(s), jnp.asarray(l))
        w = float(len(s))
        for k, v in h.items():
            agg_sums[k] = agg_sums.get(k, 0.0) + np.asarray(v, np.float64) * w
            agg_counts[k] = agg_counts.get(k, 0.0) + w
    means = {k: agg_sums[k] / agg_counts[k] for k in agg_sums}
    got = finalize_metrics(means)["auc"]
    np.testing.assert_allclose(got, _exact_auc(scores, labels), rtol=1e-6)


def test_eval_pipeline_reports_auc(tmp_path, devices):
    """End to end through the worker: a sharded, wrap-padded eval task over
    the 8-device mesh reports the same AUC as the exact pairwise AUC of the
    model's pooled predictions."""
    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.data.reader import Shard, create_data_reader
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.task_dispatcher import TASK_EVALUATION, Task
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import Worker

    n = 24  # minibatch 16 -> one full chunk + ragged tail of 8
    path = str(tmp_path / "criteo.rio")
    generate("criteo", path, n)
    config = JobConfig(
        model_def="deepfm.model_spec",
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        embedding_lookup_impl="ragged_emulated",
        training_data=path,
        minibatch_size=16,
    )
    spec = load_model_spec(
        "elasticdl_tpu.models", "deepfm.model_spec",
        buckets_per_feature=64, embedding_dim=8, hidden=(16,),
        compute_dtype="float32",
    )
    reader = create_data_reader(path)
    worker = Worker(
        config, master=None, reader=reader, spec=spec, devices=devices
    )
    worker._apply_membership(
        {"version": 0, "world_size": 1, "ranks": {"w": 0}}, initial=True
    )
    worker.state = worker.trainer.init_state(jax.random.key(0))

    shard = Shard(name=path, start=0, end=n)
    got, weight = worker._run_evaluation_task(
        Task(task_id=0, shard=shard, type=TASK_EVALUATION)
    )
    assert weight == n
    final = finalize_metrics(got)
    assert "auc" in final and AUC_POS not in final

    # Ground truth: unsharded forward, exact pairwise AUC over probs
    # QUANTIZED to the histogram grid (the histogram's exactness contract).
    records = list(reader.read_records(shard))
    batch = spec.feed(records)
    params = jax.device_get(worker.state).params
    logits = np.asarray(spec.apply(params, batch, train=False))
    probs = _quantize(1.0 / (1.0 + np.exp(-logits)))
    want = _exact_auc(probs, np.asarray(batch["labels"]))
    np.testing.assert_allclose(final["auc"], want, atol=1e-9)


def test_master_round_aggregates_auc(tmp_path):
    """Two workers report disjoint eval shards; the evaluation service's
    round result carries the pooled AUC and no raw histogram vectors."""
    from elasticdl_tpu.data.reader import Shard
    from elasticdl_tpu.master.evaluation_service import EvaluationService

    rng = np.random.RandomState(3)
    scores = _quantize(rng.rand(200))
    labels = (rng.rand(200) < 0.5).astype(np.int32)
    svc = EvaluationService(
        [Shard(name="a", start=0, end=120), Shard(name="b", start=0, end=80)],
        evaluation_steps=1,
    )
    svc.trigger(model_version=1)
    tasks = []
    while True:
        t = svc.get_task("w")
        if t is None:
            break
        tasks.append(t)
    halves = [(scores[:120], labels[:120]), (scores[120:], labels[120:])]
    for task, (s, l) in zip(tasks, halves):
        h = auc_histograms(jnp.asarray(s), jnp.asarray(l))
        metrics = {k: np.asarray(v).tolist() for k, v in h.items()}
        metrics["loss"] = 0.1
        svc.report_metrics(metrics, weight=float(len(s)))
        svc.report_task(task.task_id, success=True)
    result = svc.latest_metrics()
    assert "auc" in result and AUC_POS not in result
    np.testing.assert_allclose(
        result["auc"], _exact_auc(scores, labels), rtol=1e-6
    )


def test_job_status_with_auc_serializes_over_grpc(tmp_path):
    """JobStatus carries eval_metrics with the derived AUC; the value must
    be a plain python float or json.dumps on the gRPC wire dies (np.float64
    leaked here once — caught by the end-to-end drive)."""
    import json

    from elasticdl_tpu.common.rpc import JsonRpcClient
    from elasticdl_tpu.data.reader import Shard
    from elasticdl_tpu.master.evaluation_service import EvaluationService
    from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    svc = EvaluationService([Shard(name="a", start=0, end=8)], evaluation_steps=1)
    servicer = MasterServicer(TaskDispatcher([]), evaluation=svc)
    server = MasterServer(servicer, port=0).start()
    try:
        svc.trigger(model_version=1)
        task = svc.get_task("w")
        h = auc_histograms(
            jnp.asarray(_quantize(np.array([0.9, 0.2]))), jnp.asarray([1, 0])
        )
        client = JsonRpcClient(server.address)
        client.wait_ready(10)
        client.call("ReportTaskResult", {
            "worker_id": "w", "task_id": task.task_id, "success": True,
            "task_type": "evaluation", "weight": 2.0,
            "metrics": {k: np.asarray(v).tolist() for k, v in h.items()},
        })
        status = client.call("JobStatus", {})  # round-trips json.dumps
        assert status["eval_metrics"]["auc"] == 1.0
        json.dumps(status)  # and the local dict is plain-serializable too
    finally:
        server.stop()
