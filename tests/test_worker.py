"""Worker end-to-end: full in-process jobs (master servicer + worker loop)
over synthetic data — training with eval interleaved, checkpoint/resume, and
predict mode.  The reference's single-process master+worker integration
pattern (SURVEY.md §4)."""

import glob
import os

import numpy as np
import pytest

from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.data.synthetic import generate
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import (
    TASK_PREDICTION,
    TaskDispatcher,
)
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker

MNIST_TINY = dict(compute_dtype="float32")


def _mnist_job(tmp_path, n_train=96, n_val=32, **cfg_kwargs):
    train_path = str(tmp_path / "train.rio")
    val_path = str(tmp_path / "val.rio")
    generate("mnist", train_path, n_train)
    generate("mnist", val_path, n_val)
    config = JobConfig(
        model_def="mnist.model_spec",
        training_data=train_path,
        validation_data=val_path,
        minibatch_size=16,
        num_minibatches_per_task=2,
        **cfg_kwargs,
    )
    reader = create_data_reader(train_path)
    records_per_task = config.minibatch_size * config.num_minibatches_per_task
    dispatcher = TaskDispatcher(
        reader.create_shards(records_per_task), num_epochs=config.num_epochs
    )
    eval_reader = create_data_reader(val_path)
    evaluation = EvaluationService(
        eval_reader.create_shards(records_per_task),
        evaluation_steps=config.evaluation_steps,
    )
    servicer = MasterServicer(dispatcher, evaluation=evaluation)
    spec = load_model_spec("elasticdl_tpu.models", "mnist.model_spec", **MNIST_TINY)
    return config, servicer, reader, eval_reader, spec


def test_training_job_end_to_end(tmp_path, devices):
    config, servicer, reader, eval_reader, spec = _mnist_job(
        tmp_path, evaluation_steps=6
    )

    class MuxReader:
        """Routes read_records by shard file name (train vs val)."""

        def read_records(self, shard):
            r = reader if os.path.basename(shard.name).startswith("train") else eval_reader
            return r.read_records(shard)

    worker = Worker(
        config, DirectMasterProxy(servicer), MuxReader(),
        worker_id="w0", spec=spec, devices=devices,
    )
    result = worker.run()
    assert result["tasks_done"] >= 3
    assert result["step"] == 6  # 96 records / 16 per batch
    assert servicer.dispatcher.finished()
    status = servicer.JobStatus({})
    assert status["done"] == 3
    assert status["eval_rounds"] >= 1
    assert 0.0 <= status["eval_metrics"]["accuracy"] <= 1.0


def test_checkpoint_resume(tmp_path, devices):
    ckpt_dir = str(tmp_path / "ckpt")
    config, servicer, reader, _, spec = _mnist_job(
        tmp_path, checkpoint_dir=ckpt_dir, checkpoint_steps=2, num_epochs=1
    )
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices,
    )
    result = worker.run()
    assert result["step"] == 6
    assert servicer.GetCheckpoint({})["step"] == 6
    # The save path published the serving manifest (r10): the newest
    # COMPLETE step, atomically visible to the serving watcher.
    from elasticdl_tpu.common.checkpoint import read_manifest

    assert read_manifest(ckpt_dir)["step"] == 6

    # A fresh worker (new job resuming the same checkpoint dir) starts from
    # the saved step, not from scratch.
    config2, servicer2, reader2, _, spec2 = _mnist_job(
        tmp_path, checkpoint_dir=ckpt_dir, checkpoint_steps=2, num_epochs=1
    )
    servicer2.ReportCheckpoint({"path": ckpt_dir, "step": 6})
    worker2 = Worker(
        config2, DirectMasterProxy(servicer2), reader2,
        worker_id="w0", spec=spec2, devices=devices,
    )
    result2 = worker2.run()
    assert result2["step"] == 12  # resumed at 6, ran 6 more


def test_prediction_job(tmp_path, devices):
    data = str(tmp_path / "pred.rio")
    generate("mnist", data, 40)
    out_dir = str(tmp_path / "outputs")
    config = JobConfig(
        model_def="mnist.model_spec",
        job_type="prediction",
        minibatch_size=16,
        prediction_outputs=out_dir,
    )
    reader = create_data_reader(data)
    dispatcher = TaskDispatcher(
        reader.create_shards(20), task_type=TASK_PREDICTION
    )
    servicer = MasterServicer(dispatcher)
    spec = load_model_spec("elasticdl_tpu.models", "mnist.model_spec", **MNIST_TINY)
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices,
    )
    worker.run()
    files = sorted(glob.glob(os.path.join(out_dir, "*.npy")))
    assert len(files) == 2
    outputs = np.concatenate([np.load(f) for f in files])
    assert outputs.shape == (40, 10)  # logits for every record, none dropped


def test_partial_tail_batch(tmp_path, devices):
    """A shard not divisible by minibatch_size still trains (wrap-padded)."""
    data = str(tmp_path / "t.rio")
    generate("mnist", data, 25)
    config = JobConfig(model_def="mnist.model_spec", minibatch_size=16)
    reader = create_data_reader(data)
    servicer = MasterServicer(TaskDispatcher(reader.create_shards(25)))
    spec = load_model_spec("elasticdl_tpu.models", "mnist.model_spec", **MNIST_TINY)
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices,
    )
    result = worker.run()
    assert result["tasks_done"] == 1
    assert result["step"] == 2


def test_standalone_eval_job_restores_local_checkpoint(tmp_path, devices):
    """A FRESH master (standalone evaluation job) has no reported checkpoint,
    but the worker must still restore from the local checkpoint directory —
    gating on the master's GetCheckpoint made such jobs silently score
    freshly-initialized weights."""
    from elasticdl_tpu.master.task_dispatcher import TASK_EVALUATION

    ckpt_dir = str(tmp_path / "ckpt")
    config, servicer, reader, _, spec = _mnist_job(
        tmp_path, checkpoint_dir=ckpt_dir, checkpoint_steps=2, num_epochs=1
    )
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices,
    )
    assert worker.run()["step"] == 6

    # Standalone eval job: brand-new master, NOTHING reported to it.
    val = str(tmp_path / "standalone_val.rio")
    generate("mnist", val, 32)
    eval_config = JobConfig(
        model_def="mnist.model_spec",
        job_type="evaluation",
        validation_data=val,
        minibatch_size=16,
        checkpoint_dir=ckpt_dir,
    )
    eval_reader = create_data_reader(val)
    dispatcher = TaskDispatcher(
        eval_reader.create_shards(16), task_type=TASK_EVALUATION
    )
    eval_servicer = MasterServicer(dispatcher)
    assert eval_servicer.GetCheckpoint({}).get("path") is None  # fresh master
    w2 = Worker(
        eval_config, DirectMasterProxy(eval_servicer), eval_reader,
        worker_id="w0", spec=spec, devices=devices,
    )
    result = w2.run()
    assert result["step"] == 6  # trained weights adopted, not fresh init


def test_eval_job_without_restorable_checkpoint_fails_loud(tmp_path, devices):
    """Evaluation with a checkpoint_dir that holds nothing restorable must
    refuse to run — scoring random weights would be silent garbage."""
    from elasticdl_tpu.master.task_dispatcher import TASK_EVALUATION

    val = str(tmp_path / "val.rio")
    generate("mnist", val, 32)
    config = JobConfig(
        model_def="mnist.model_spec",
        job_type="evaluation",
        validation_data=val,
        minibatch_size=16,
        checkpoint_dir=str(tmp_path / "empty_ckpt"),
    )
    reader = create_data_reader(val)
    dispatcher = TaskDispatcher(
        reader.create_shards(16), task_type=TASK_EVALUATION
    )
    servicer = MasterServicer(dispatcher)
    spec = load_model_spec("elasticdl_tpu.models", "mnist.model_spec", **MNIST_TINY)
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices,
    )
    with pytest.raises(RuntimeError, match="no restorable checkpoint"):
        worker.run()


def test_failed_step_recovers_state(tmp_path, devices):
    """A step failure mid-task must not leave the worker holding donated
    buffers: it adopts the last-good state from TrainLoopError (or rebuilds
    from checkpoint), the task is reported failed + requeued, and the job
    still completes (r4 regression: one bad step used to wedge every
    subsequent task on deleted arrays)."""
    from elasticdl_tpu.parallel.trainer import Trainer

    config, servicer, reader, eval_reader, spec = _mnist_job(tmp_path)

    orig = Trainer.train_step
    fail = {"armed": True}

    def flaky(self, state, batch):
        state, metrics = orig(self, state, batch)
        if fail["armed"]:
            fail["armed"] = False
            # the input state was donated by the call above; a failure NOW
            # mimics a step crash after consumption
            raise RuntimeError("injected step failure")
        return state, metrics

    Trainer.train_step = flaky
    try:
        worker = Worker(
            config, DirectMasterProxy(servicer), reader,
            worker_id="w0", spec=spec, devices=devices,
        )
        result = worker.run()
    finally:
        Trainer.train_step = orig
    assert servicer.dispatcher.finished()
    assert result["step"] >= 6  # all shards trained (failed task re-run)
    status = servicer.JobStatus({})
    assert status["done"] == 3 and status["todo"] == 0


def test_corrupt_recordio_fails_task_cleanly(tmp_path, devices):
    """A shard whose payload got corrupted on disk must fail ITS task loudly
    (CRC catch in the bulk C++ read) without wedging the worker; the healthy
    shards complete and the corrupt one lands in the abandoned count after
    its retry budget."""
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    data = str(tmp_path / "t.rio")
    generate("mnist", data, 48)
    # Corrupt one byte inside the SECOND shard's records (records 16-31).
    from elasticdl_tpu.data.recordio import RecordIOReader

    offsets = RecordIOReader(data).index()
    with open(data, "r+b") as f:
        f.seek(offsets[20] + 12)  # inside record 20's payload
        b = f.read(1)
        f.seek(offsets[20] + 12)
        f.write(bytes([b[0] ^ 0xFF]))

    config = JobConfig(model_def="mnist.model_spec", minibatch_size=16)
    reader = create_data_reader(data)
    dispatcher = TaskDispatcher(
        reader.create_shards(16), max_task_retries=2
    )
    servicer = MasterServicer(dispatcher)
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", **MNIST_TINY
    )
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices,
    )
    result = worker.run()
    status = servicer.JobStatus({})
    assert status["finished"]
    assert status["done"] == 2          # healthy shards trained
    assert status["abandoned"] == 1     # corrupt shard burned its retries
    assert result["step"] == 2          # 2 healthy tasks x 1 step each


def test_prep_ahead_pipeline_matches_synchronous(tmp_path, devices):
    """The prep-ahead pipeline (fused + pipelined defaults) must complete
    the same job to the same step count as the fully synchronous path, with
    every task reported exactly once — three tasks are in flight at peak
    (prepped / dispatched / pending-report), and a drain point (job end)
    must settle all of them."""
    results = {}
    for label, flags in (
        ("prep_ahead", dict()),  # defaults: fused + pipelined -> prep-ahead
        ("synchronous", dict(task_pipelining=False)),
    ):
        config, servicer, reader, _, spec = _mnist_job(
            tmp_path / label, num_epochs=1, **flags
        )
        worker = Worker(
            config, DirectMasterProxy(servicer), reader,
            worker_id="w0", spec=spec, devices=devices,
        )
        results[label] = (worker.run(), servicer, worker)
    for label, (result, servicer, _worker) in results.items():
        assert result["step"] == 6, label
        assert servicer.dispatcher.finished(), label
        assert servicer.JobStatus({})["done"] == 3, label
    # Prep-ahead must actually have engaged: the background pool is created
    # lazily on the first _submit_prep, so its existence proves the path ran
    # (tasks_done alone would pass identically on the plain pipelined path).
    assert results["prep_ahead"][0]["tasks_done"] == 3
    assert results["prep_ahead"][2]._prep_pool is not None
    assert results["synchronous"][2]._prep_pool is None


def test_prep_ahead_read_failure_fails_that_task_only(tmp_path, devices):
    """A prep (background read/decode) failure must fail THAT task's report
    — requeued by the master — while the job still completes, mirroring the
    inline dispatch path's contract."""
    config, servicer, reader, _, spec = _mnist_job(tmp_path, num_epochs=1)

    class FlakyReader:
        """First read of task-shard 1 raises; retries succeed."""

        def __init__(self):
            self.failed = False

        def read_records(self, shard):
            if shard.start == 32 and not self.failed:
                self.failed = True
                raise RuntimeError("injected prep-read failure")
            return reader.read_records(shard)

    worker = Worker(
        config, DirectMasterProxy(servicer), FlakyReader(),
        worker_id="w0", spec=spec, devices=devices,
    )
    result = worker.run()
    assert result["step"] == 6  # every record still trained once
    assert servicer.dispatcher.finished()
    status = servicer.JobStatus({})
    assert status["done"] == 3


def test_background_checkpoint_failure_rolls_back_and_retries(
    tmp_path, devices
):
    """A failed background periodic save must roll the watermark back so a
    later boundary retries — and the job itself must not fail (the save
    runs off the task loop's critical path)."""
    ckpt_dir = str(tmp_path / "ckpt")
    # checkpoint_steps=4 makes the retry OBSERVABLE only via the rollback:
    # the step-4 save fails; with the watermark rolled back to 0 the step-6
    # boundary fires (6-0 >= 4), without it 6-4 < 4 would never retry (the
    # job-end final save bypasses _save_snapshot, so calls stay at 1).
    config, servicer, reader, _, spec = _mnist_job(
        tmp_path, num_epochs=1, checkpoint_dir=ckpt_dir, checkpoint_steps=4
    )
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices,
    )
    calls = {"n": 0}
    orig = Worker._save_snapshot

    def flaky(self, step, wait=False, state=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected background save failure")
        return orig(self, step, wait=wait, state=state)

    worker._save_snapshot = flaky.__get__(worker)
    result = worker.run()
    assert result["step"] == 6
    assert calls["n"] >= 2, "rolled-back watermark never retried"
    # The final checkpoint is durable and reported despite the early
    # failure; a fresh manager can restore it.
    assert servicer.GetCheckpoint({})["step"] == 6
    from elasticdl_tpu.common.checkpoint import CheckpointManager

    mgr = CheckpointManager(ckpt_dir)
    assert 6 in mgr.all_steps()
    mgr.close()


def test_phase_time_decomposition(tmp_path, devices):
    """r6: the worker decomposes its task-loop wall into named phases
    (prep_wait/dispatch/step_wait/metrics/checkpoint/control), the snapshot
    rides its reports, and the master republishes it via JobStatus — the
    instrument that turns the job-vs-bench throughput gap from a guess into
    named phases."""
    import time as _time

    from elasticdl_tpu.common.metrics import (
        CRITICAL_PATH_PHASES,
        critical_path_seconds,
    )

    ckpt_dir = str(tmp_path / "ckpt")
    config, servicer, reader, _, spec = _mnist_job(
        tmp_path, num_epochs=1, checkpoint_dir=ckpt_dir, checkpoint_steps=2
    )
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices,
    )
    t0 = _time.perf_counter()
    result = worker.run()
    wall = _time.perf_counter() - t0
    phases = result["phase_times"]
    # the task loop's wall-consuming phases are all present...
    for name in ("prep_wait", "dispatch", "step_wait", "metrics",
                 "checkpoint", "control", "lease_wait"):
        assert name in phases, (name, phases)
        assert phases[name] >= 0.0
    # ...off-path extras are limited to the two concurrent-time buckets
    # (background checkpoint write, ingest-pool parallel decode)
    assert set(phases) - set(CRITICAL_PATH_PHASES) <= {
        "checkpoint_bg", "decode_parallel",
    }
    # ...and their sum is a decomposition of (bounded by) the run's wall
    crit = critical_path_seconds(phases)
    assert 0.0 < crit <= wall, (crit, wall)
    # the master's JobStatus republishes the same snapshot per worker
    status = servicer.JobStatus({})
    assert "w0" in status["phase_times"]
    assert critical_path_seconds(status["phase_times"]["w0"]) > 0.0


def test_phase_timers_nested_self_time():
    """A phase entered inside another (e.g. a membership change inside the
    ``control`` heartbeat draining a pipelined task through its
    dispatch/metrics phases) records SELF-time: each second lands in
    exactly one bucket, so the decomposition stays bounded by wall — the
    r6 instrument must not over-attribute whole task durations to the
    control plane."""
    import threading
    import time as _time

    from elasticdl_tpu.common.metrics import (
        PhaseTimers,
        critical_path_seconds,
    )

    pt = PhaseTimers()
    t0 = _time.perf_counter()
    with pt.phase("control"):
        _time.sleep(0.02)
        with pt.phase("dispatch"):
            _time.sleep(0.05)
            with pt.phase("metrics"):
                _time.sleep(0.02)
        _time.sleep(0.01)
    wall = _time.perf_counter() - t0
    snap = pt.snapshot()
    # each phase saw at least its own sleeps (no strict upper bounds:
    # sleeps overshoot freely on a starved box, and the overshoot lands
    # in whichever phase was open)...
    assert snap["metrics"] >= 0.02 - 1e-4, snap
    assert snap["dispatch"] >= 0.05 - 1e-4, snap
    assert snap["control"] >= 0.03 - 1e-4, snap
    # ...and the load-independent discriminator: the sum stays bounded by
    # the outer wall.  Double-counting nested wall (the bug this guards
    # against) would make the sum ~2x the sleeps and exceed it.
    assert critical_path_seconds(snap) <= wall, (snap, wall)

    # the nesting stack is per-thread: a background phase must not
    # subtract from a concurrently open foreground phase
    def bg():
        with pt.phase("checkpoint_bg"):
            _time.sleep(0.03)

    with pt.phase("checkpoint"):
        t = threading.Thread(target=bg)
        t.start()
        t.join()
    snap = pt.snapshot()
    assert snap["checkpoint"] >= 0.03 - 1e-4, snap
    assert snap["checkpoint_bg"] >= 0.03 - 1e-4, snap


# ---------------- parallel ingest engine (r9) ----------------


class _RecordingMaster:
    """Minimal master double for unit-testing abandon paths: records
    ReportTaskResult payloads, answers nothing else."""

    def __init__(self):
        self.reports = []

    def call(self, method, request):
        assert method == "ReportTaskResult", method
        self.reports.append(dict(request))
        return {"accepted": True}


def _task_of(reader, task_id, start, end):
    from elasticdl_tpu.data.reader import Shard
    from elasticdl_tpu.master.task_dispatcher import TASK_TRAINING, Task

    shard = reader.sources()[0]
    return Task(task_id, Shard(shard, start, end), TASK_TRAINING, 0)


def test_parallel_prep_bit_identical_to_serial(tmp_path, devices):
    """The tentpole contract: threaded shard decode reassembles to exactly
    the serial path's [T, mb, ...] stack, tail records, and counts — on an
    mb-unaligned shard, so ragged-tail masking and gradient weighting
    cannot drift."""
    from elasticdl_tpu.data.synthetic import generate as _gen

    data = str(tmp_path / "ragged.rio")
    _gen("mnist", data, 56)  # mb=16: 3 full minibatches + 8-record tail
    reader = create_data_reader(data)
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", **MNIST_TINY
    )
    preps = {}
    for label, threads in (("serial", 1), ("parallel", 4)):
        config = JobConfig(
            model_def="mnist.model_spec", training_data=data,
            minibatch_size=16, ingest_threads=threads,
        )
        w = Worker(
            config, _RecordingMaster(), reader,
            worker_id=label, spec=spec, devices=devices,
        )
        preps[label] = w._prep_fused_host(_task_of(reader, 0, 0, 56))
        if threads > 1:
            assert w._ingest is not None and w._ingest.parallel
    s, p = preps["serial"], preps["parallel"]
    assert (s.total, s.n_full) == (p.total, p.n_full) == (56, 3)
    assert list(s.tail) == list(p.tail) and len(p.tail) == 8
    assert set(s.stacked) == set(p.stacked)
    for k in s.stacked:
        assert s.stacked[k].dtype == p.stacked[k].dtype
        assert s.stacked[k].shape == p.stacked[k].shape == (3, 16) + (
            s.stacked[k].shape[2:]
        )
        np.testing.assert_array_equal(s.stacked[k], p.stacked[k])


def test_k_deep_prep_pipeline_matches_synchronous(tmp_path, devices):
    """prep_depth=3 holds up to three leased tasks in concurrent prep; the
    job must complete to the same step count as the synchronous path with
    every task reported exactly once."""
    results = {}
    for label, flags in (
        ("deep", dict(prep_depth=3, ingest_threads=2)),
        ("synchronous", dict(task_pipelining=False)),
    ):
        config, servicer, reader, _, spec = _mnist_job(
            tmp_path / label, num_epochs=2, **flags
        )
        worker = Worker(
            config, DirectMasterProxy(servicer), reader,
            worker_id="w0", spec=spec, devices=devices,
        )
        results[label] = (worker.run(), servicer, worker)
    for label, (result, servicer, _w) in results.items():
        assert result["step"] == 12, label  # 2 epochs x 6 steps
        assert servicer.dispatcher.finished(), label
        assert servicer.JobStatus({})["done"] == 6, label
    deep_worker = results["deep"][2]
    assert deep_worker._prep_pool is not None
    assert deep_worker._prep_pool._max_workers == 3
    assert not deep_worker._prep_queue  # job end drained every slot


def test_k_deep_prep_abandon_reports_each_exactly_once(tmp_path, devices):
    """Preemption containment for the k-deep queue: every queued prep is
    failure-reported exactly once (immediate master requeue), futures are
    settled or cancelled, a second abandon is a no-op, and no prep threads
    leak beyond the bounded pool."""
    import threading

    data = str(tmp_path / "t.rio")
    from elasticdl_tpu.data.synthetic import generate as _gen

    _gen("mnist", data, 96)
    reader = create_data_reader(data)
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", **MNIST_TINY
    )
    config = JobConfig(
        model_def="mnist.model_spec", training_data=data,
        minibatch_size=16, prep_depth=3,
    )
    master = _RecordingMaster()
    # Other workers in this test process keep their own (bounded) pools
    # alive; only THIS worker's thread growth is under test.
    threads_before = {
        t for t in threading.enumerate() if t.name.startswith("edl-prep")
    }
    w = Worker(config, master, reader, worker_id="w0", spec=spec,
               devices=devices)
    for i, (a, b) in enumerate(((0, 32), (32, 64), (64, 96))):
        task = _task_of(reader, i, a, b)
        report = {"worker_id": "w0", "task_id": i,
                  "task_type": task.type, "success": True}
        w._prep_queue.append((task, report, w._submit_prep(task)))
    entries = list(w._prep_queue)
    w._abandon_prep()
    assert not w._prep_queue
    assert sorted(r["task_id"] for r in master.reports) == [0, 1, 2]
    assert all(r["success"] is False for r in master.reports)
    w._abandon_prep()  # idempotent: nothing left to report
    assert len(master.reports) == 3
    # futures settle (run to completion or cancelled) — no orphaned work
    for _task, _report, fut in entries:
        fut.cancel()
        fut.cancelled() or fut.result(timeout=30)
    # bounded pool: this worker added at most prep_depth threads
    new_threads = {
        t for t in threading.enumerate() if t.name.startswith("edl-prep")
    } - threads_before
    assert len(new_threads) <= 3


def test_abandon_leases_returns_tasks_and_group_mode_drops(tmp_path, devices):
    """Unstarted lease buffer entries are failure-reported (requeue now,
    not at timeout) in single-worker mode; in group mode the buffer is
    lockstep-log read-ahead the master already invalidates, so it is
    dropped without reports (a report would double-requeue)."""
    data = str(tmp_path / "t.rio")
    from elasticdl_tpu.data.synthetic import generate as _gen

    _gen("mnist", data, 64)
    reader = create_data_reader(data)
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", **MNIST_TINY
    )
    config = JobConfig(model_def="mnist.model_spec", training_data=data,
                       minibatch_size=16)
    master = _RecordingMaster()
    w = Worker(config, master, reader, worker_id="w0", spec=spec,
               devices=devices)
    t0 = _task_of(reader, 7, 0, 32).to_dict()
    t1 = _task_of(reader, 8, 32, 64).to_dict()
    w._leased.extend(
        {"task": t, "finished": False, "stale": False} for t in (t0, t1)
    )
    w._abandon_leases()
    assert not w._leased
    assert sorted(r["task_id"] for r in master.reports) == [7, 8]
    assert all(r["success"] is False for r in master.reports)

    # group mode: drop, never report
    w._leased.append({"task": t0, "finished": False, "stale": False})
    w._group_mode = True
    w._abandon_leases()
    assert not w._leased and len(master.reports) == 2


def test_membership_change_drains_prep_and_returns_leases(tmp_path, devices):
    """A membership change mid-run under the full r9 pipeline (k-deep prep,
    batched leases): prepped tasks dispatch on the OLD mesh, buffered
    leases go back to the master for immediate requeue, the mesh re-forms,
    and the job completes with every shard trained exactly once."""
    config, servicer, reader, _, spec = _mnist_job(
        tmp_path, num_epochs=1, prep_depth=2,
    )
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices, devices_per_worker=4,
    )
    orig_get_task = servicer.GetTask
    calls = {"n": 0}

    def get_task_with_join(req):
        calls["n"] += 1
        if calls["n"] == 1:
            # Phantom joins during the FIRST (batched) lease: the version
            # bump is noticed at the next heartbeat, while the prep queue
            # and lease buffer still hold this batch's tasks.
            servicer.rendezvous.register("phantom")
        return orig_get_task(req)

    servicer.GetTask = get_task_with_join
    result = worker.run()
    assert result["reforms"] == 1
    assert servicer.dispatcher.finished()
    status = servicer.JobStatus({})
    assert status["done"] == 3 and status["todo"] == 0
    assert result["step"] == 6  # nothing trained twice, nothing skipped
    assert not worker._prep_queue and not worker._leased


def test_eval_pending_heartbeat_returns_leases(tmp_path, devices):
    """An eval_pending heartbeat makes a lease-holding worker return its
    buffer (requeue-flagged, budget untouched) so the round is not delayed
    by lease_batch-1 tasks of version skew."""
    data = str(tmp_path / "t.rio")
    generate("mnist", data, 64)
    reader = create_data_reader(data)
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", **MNIST_TINY
    )
    config = JobConfig(model_def="mnist.model_spec", training_data=data,
                       minibatch_size=16)

    class HintingMaster:
        def __init__(self):
            self.reports = []

        def call(self, method, request):
            if method == "Heartbeat":
                return {"version": -1, "eval_pending": True}
            if method == "ReportTaskResult":
                self.reports.append(dict(request))
                return {"accepted": True}
            raise AssertionError(method)

    master = HintingMaster()
    w = Worker(config, master, reader, worker_id="w0", spec=spec,
               devices=devices)
    t = _task_of(reader, 5, 0, 32).to_dict()
    w._leased.append({"task": t, "finished": False, "stale": False})
    w._check_membership()  # version matches (-1): no re-form, just the hint
    assert not w._leased
    assert [r["task_id"] for r in master.reports] == [5]
    assert master.reports[0]["requeue"] is True


def test_prep_pool_serializes_for_non_thread_safe_readers(tmp_path, devices):
    """A reader that does not declare thread_safe_ranges keeps the one-
    thread prep pool even at prep_depth>1 — concurrent _read_records calls
    are exactly what such readers forbid (reader.py contract)."""
    config, servicer, reader, _, spec = _mnist_job(
        tmp_path, num_epochs=1, prep_depth=3
    )

    class OpaqueReader:  # no thread_safe_ranges attribute -> default False
        def read_records(self, shard):
            return reader.read_records(shard)

    w = Worker(
        config, DirectMasterProxy(servicer), OpaqueReader(),
        worker_id="w0", spec=spec, devices=devices,
    )
    w._submit_prep(_task_of(reader, 0, 0, 32)).result(timeout=30)
    assert w._prep_pool._max_workers == 1
    # ...while a range-safe reader gets the full prep_depth width
    config2, servicer2, reader2, _, spec2 = _mnist_job(
        tmp_path / "safe", num_epochs=1, prep_depth=3
    )
    w2 = Worker(
        config2, DirectMasterProxy(servicer2), reader2,
        worker_id="w0", spec=spec2, devices=devices,
    )
    w2._submit_prep(_task_of(reader2, 0, 0, 32)).result(timeout=30)
    assert w2._prep_pool._max_workers == 3


def test_draining_heartbeat_returns_prep_queue_and_leases(tmp_path, devices):
    """The max-steps draining hint returns BOTH the lease buffer and the
    undispatched prep queue (no device work in either); the stopped
    dispatcher drops them, so overshoot shrinks to dispatched tasks."""
    data = str(tmp_path / "t.rio")
    generate("mnist", data, 96)
    reader = create_data_reader(data)
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", **MNIST_TINY
    )
    config = JobConfig(model_def="mnist.model_spec", training_data=data,
                       minibatch_size=16, prep_depth=2)

    class DrainingMaster:
        def __init__(self):
            self.reports = []

        def call(self, method, request):
            if method == "Heartbeat":
                return {"version": -1, "draining": True}
            if method == "ReportTaskResult":
                self.reports.append(dict(request))
                return {"accepted": True}
            raise AssertionError(method)

    master = DrainingMaster()
    w = Worker(config, master, reader, worker_id="w0", spec=spec,
               devices=devices)
    t0 = _task_of(reader, 0, 0, 32)
    w._prep_queue.append(
        (t0, {"worker_id": "w0", "task_id": 0, "task_type": t0.type,
              "success": True}, w._submit_prep(t0))
    )
    w._leased.append(
        {"task": _task_of(reader, 1, 32, 64).to_dict(),
         "finished": False, "stale": False}
    )
    w._check_membership()
    assert not w._prep_queue and not w._leased
    assert sorted(r["task_id"] for r in master.reports) == [0, 1]
    assert all(
        r["requeue"] is True and r["success"] is False
        for r in master.reports
    )
