"""Master->survivor death push (VERDICT r4 Weak #3 / Next #4).

A survivor blocked in a collective on a dead peer used to wait out the
jax.distributed coordination heartbeat (default 30 s) before restarting.
``Worker.death_watch_tick`` — run from the liveness-heartbeat thread —
polls the master's membership and forces the RESTART exit within the grace
window of the master's eviction.  These tests drive the decision function
directly with a fake master; the real-process path is measured by
tools/rendezvous_bench.py.
"""

from __future__ import annotations

import pytest

from elasticdl_tpu.common.config import JobConfig


class _FakeMaster:
    def __init__(self):
        self.membership = {
            "version": 0,
            "world_size": 2,
            "ranks": {"w-a": 0, "w-b": 1},
            "addresses": {"w-a": "h1:1", "w-b": "h2:1"},
        }

    def call(self, method, req):
        assert method == "GetMembership"
        return dict(self.membership)


def _mk_worker(master, **cfg):
    from elasticdl_tpu.worker.worker import Worker

    config = JobConfig(
        model_def="mnist.model_spec", training_data="x", multihost=True, **cfg
    )
    w = Worker.__new__(Worker)  # no trainer/devices needed for the tick
    w.config = config
    w.master = master
    w.worker_id = "w-a"
    w._membership_version = 0
    w._ranks = {"w-a": 0, "w-b": 1}
    w._addresses = {"w-a": "h1:1", "w-b": "h2:1"}
    w._group_mode = True
    return w


def test_departure_forces_restart_after_grace():
    master = _FakeMaster()
    w = _mk_worker(master)
    state = {"pending_since": None}
    # Peer dies: master evicts it, version bumps.
    master.membership = {
        "version": 1, "world_size": 1,
        "ranks": {"w-a": 0}, "addresses": {"w-a": "h1:1"},
    }
    assert w.death_watch_tick(state, now=100.0) is False  # arms the window
    assert state["pending_since"] == 100.0
    assert w.death_watch_tick(state, now=101.0) is False  # inside grace
    assert w.death_watch_tick(state, now=102.5) is True   # grace expired


def test_main_thread_winning_race_disarms():
    master = _FakeMaster()
    w = _mk_worker(master)
    state = {"pending_since": None}
    master.membership = {
        "version": 1, "world_size": 1,
        "ranks": {"w-a": 0}, "addresses": {"w-a": "h1:1"},
    }
    assert w.death_watch_tick(state, now=100.0) is False
    # Main thread applied the change (it was between steps, not blocked).
    w._membership_version = 1
    w._ranks = {"w-a": 0}
    w._addresses = {"w-a": "h1:1"}
    assert w.death_watch_tick(state, now=105.0) is False
    assert state["pending_since"] is None


def test_pure_join_never_forces():
    master = _FakeMaster()
    w = _mk_worker(master)
    state = {"pending_since": None}
    master.membership = {
        "version": 1, "world_size": 3,
        "ranks": {"w-a": 0, "w-b": 1, "w-c": 2},
        "addresses": {"w-a": "h1:1", "w-b": "h2:1", "w-c": "h3:1"},
    }
    for now in (100.0, 105.0, 200.0):
        assert w.death_watch_tick(state, now=now) is False
    assert state["pending_since"] is None  # never even armed


def test_identical_topology_churn_never_forces():
    master = _FakeMaster()
    w = _mk_worker(master)
    state = {"pending_since": None}
    master.membership["version"] = 2  # same ranks+addresses, new version
    for now in (100.0, 200.0):
        assert w.death_watch_tick(state, now=now) is False
    assert state["pending_since"] is None


def test_disabled_by_grace_flag_and_non_group_mode():
    master = _FakeMaster()
    master.membership = {
        "version": 1, "world_size": 1,
        "ranks": {"w-a": 0}, "addresses": {"w-a": "h1:1"},
    }
    w = _mk_worker(master, death_push_grace_s=0.0)
    state = {"pending_since": None}
    for now in (100.0, 200.0):
        assert w.death_watch_tick(state, now=now) is False

    w2 = _mk_worker(master)
    w2._group_mode = False  # lone worker: no collective to be stuck in
    for now in (100.0, 200.0):
        assert w2.death_watch_tick(state, now=now) is False


def test_master_unreachable_keeps_window():
    master = _FakeMaster()
    w = _mk_worker(master)
    state = {"pending_since": None}
    master.membership = {
        "version": 1, "world_size": 1,
        "ranks": {"w-a": 0}, "addresses": {"w-a": "h1:1"},
    }
    assert w.death_watch_tick(state, now=100.0) is False

    def boom(method, req):
        raise ConnectionError("master briefly down")

    w.master = type("M", (), {"call": staticmethod(boom)})()
    assert w.death_watch_tick(state, now=105.0) is False
    assert state["pending_since"] == 100.0  # window survives the blip
    w.master = master
    assert w.death_watch_tick(state, now=105.0) is True
