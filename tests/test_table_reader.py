"""Table (ODPS-parity) reader: shard math, column selection, routing, format
sniffing, and an end-to-end census job reading from a table instead of files
(SURVEY.md §2 #14)."""

import sqlite3

import numpy as np
import pytest

from elasticdl_tpu.data.reader import CompositeDataReader, create_data_reader
from elasticdl_tpu.data.table import TableDataReader, write_table


@pytest.fixture()
def db(tmp_path):
    path = str(tmp_path / "data.db")
    rows = [(i, f"name{i}", i * 0.5) for i in range(25)]
    write_table(path, rows, ["id", "name", "score"])
    return path


def test_shards_and_ranges(db):
    reader = TableDataReader(db)
    shards = reader.create_shards(10)
    assert [(s.start, s.end) for s in shards] == [(0, 10), (10, 20), (20, 25)]
    assert shards[0].name.endswith("#records")
    recs = list(reader.read_records(shards[1]))
    assert len(recs) == 10
    assert recs[0] == b"10,name10,5.0"


def test_column_selection_and_delimiter(db):
    reader = TableDataReader(db, columns=["score", "id"], delimiter="\t")
    [shard] = reader.create_shards(100)
    recs = list(reader.read_records(shard))
    assert recs[3] == b"1.5\t3"


def test_unknown_column_and_table(db):
    with pytest.raises(ValueError, match="unknown columns"):
        TableDataReader(db, columns=["nope"])
    with pytest.raises(ValueError, match="no table"):
        TableDataReader(db, table="nope")


def test_multi_table_requires_selection(tmp_path):
    path = str(tmp_path / "multi.db")
    write_table(path, [(1,)], ["a"], table="t1")
    conn = sqlite3.connect(path)
    conn.execute("CREATE TABLE t2 (b)")
    conn.commit()
    conn.close()
    with pytest.raises(ValueError, match="several tables"):
        TableDataReader(path)
    reader = TableDataReader(path, table="t1")
    assert reader.sources() == [f"{path}#t1"]


def test_create_data_reader_sniffs_sqlite(db):
    reader = create_data_reader(db)
    assert isinstance(reader, TableDataReader)
    # path#table selection through the factory
    reader2 = create_data_reader(f"{db}#records")
    [shard] = reader2.create_shards(1000)
    assert shard.size == 25


def test_composite_routing_across_table_and_csv(db, tmp_path):
    csv = tmp_path / "extra.csv"
    csv.write_text("x,y\n1,2\n")
    composite = CompositeDataReader(
        [create_data_reader(db), create_data_reader(str(csv))]
    )
    shards = composite.create_shards(100)
    by_source = {s.name: s for s in shards}
    assert len(by_source) == 2
    for shard in shards:
        assert list(composite.read_records(shard))


def test_sparse_rowids_after_deletion(tmp_path):
    """Deleted rows break rowid density; the reader must fall back to
    OFFSET pagination and still serve every surviving row exactly once."""
    path = str(tmp_path / "holes.db")
    write_table(path, [(i,) for i in range(30)], ["v"])
    conn = sqlite3.connect(path)
    conn.execute("DELETE FROM records WHERE v % 3 = 0")
    conn.commit()
    conn.close()
    reader = TableDataReader(path)
    shards = reader.create_shards(7)
    got = [r for s in shards for r in reader.read_records(s)]
    assert sorted(int(r) for r in got) == [
        i for i in range(30) if i % 3 != 0
    ]


def test_filename_with_hash_char(tmp_path):
    """'#' in a real filename must not be eaten by the table-name syntax."""
    weird = tmp_path / "part#1.csv"
    weird.write_text("a,b\nc,d\n")
    reader = create_data_reader(str(weird))
    [shard] = reader.create_shards(10)
    assert list(reader.read_records(shard)) == [b"a,b", b"c,d"]


def test_db_directory_composite(tmp_path):
    d = tmp_path / "dbs"
    d.mkdir()
    write_table(str(d / "a.db"), [(1,), (2,)], ["x"])
    write_table(str(d / "b.db"), [(3,)], ["x"])
    reader = create_data_reader(str(d))
    shards = reader.create_shards(10)
    got = sorted(
        int(r) for s in shards for r in reader.read_records(s)
    )
    assert got == [1, 2, 3]


def test_null_values_serialize_empty(tmp_path):
    path = str(tmp_path / "nulls.db")
    write_table(path, [(1, None), (None, "b")], ["a", "b"])
    reader = TableDataReader(path)
    [shard] = reader.create_shards(10)
    assert list(reader.read_records(shard)) == [b"1,", b",b"]


def test_census_job_from_table(tmp_path, devices):
    """Full worker loop with training data in a table: the reference's
    ODPS-backed training path."""
    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker

    csv_path = str(tmp_path / "census.csv")
    generate("census", csv_path, 64)
    rows = [
        line.split(",")
        for line in open(csv_path).read().splitlines()
        if line
    ]
    path = str(tmp_path / "census.db")
    write_table(
        path,
        rows,
        ["label", "age", "education_num", "capital_gain", "capital_loss",
         "hours_per_week", "workclass", "education", "marital_status",
         "occupation", "relationship", "race", "sex", "native_country",
         "extra_cat"],
    )
    config = JobConfig(
        model_def="wide_deep.model_spec",
        model_params="compute_dtype=float32;buckets=64;hidden=8",
        training_data=path,
        minibatch_size=16,
        num_minibatches_per_task=2,
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
    )
    reader = create_data_reader(path)
    dispatcher = TaskDispatcher(reader.create_shards(32), num_epochs=1)
    servicer = MasterServicer(dispatcher)
    spec = load_model_spec(
        "elasticdl_tpu.models", "wide_deep.model_spec",
        **config.parsed_model_params(),
    )
    worker = Worker(config, DirectMasterProxy(servicer), reader, spec=spec)
    result = worker.run()
    assert result["tasks_done"] == 2
    assert servicer.job_finished()
