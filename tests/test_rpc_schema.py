"""Wire-contract schemas (VERDICT r2 Missing #5): malformed requests fail AT
THE BOUNDARY — server aborts INVALID_ARGUMENT naming the field, client
raises before the wire — instead of dying as a KeyError deep in a handler."""

import grpc
import pytest

from elasticdl_tpu.common.rpc import (
    MASTER_SCHEMAS,
    JsonRpcClient,
    SchemaError,
    validate_message,
)
from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher


def test_schema_table_matches_method_table():
    servicer = MasterServicer(TaskDispatcher([]))
    assert set(servicer.method_table()) == set(MASTER_SCHEMAS)


def test_validate_message_reports_all_problems():
    with pytest.raises(SchemaError, match="unknown method"):
        validate_message("Bogus", {}, MASTER_SCHEMAS)
    with pytest.raises(SchemaError, match="worker_id"):
        validate_message("GetTask", {}, MASTER_SCHEMAS)
    with pytest.raises(SchemaError, match="must be int"):
        validate_message(
            "GetGroupTask",
            {"worker_id": "w", "seq": "zero", "version": 1},
            MASTER_SCHEMAS,
        )
    # multiple violations all named
    with pytest.raises(SchemaError, match="task_id.*success|success.*task_id"):
        validate_message(
            "ReportTaskResult", {"worker_id": "w"}, MASTER_SCHEMAS
        )
    # optional fields: absent ok, wrong type rejected
    validate_message(
        "Heartbeat", {"worker_id": "w"}, MASTER_SCHEMAS
    )
    with pytest.raises(SchemaError, match="version"):
        validate_message(
            "Heartbeat", {"worker_id": "w", "version": "v2"}, MASTER_SCHEMAS
        )
    # unknown extra fields pass (forward compatibility)
    validate_message(
        "GetTask", {"worker_id": "w", "future_field": 1}, MASTER_SCHEMAS
    )
    # bool is NOT an int at this boundary (bool subclasses int in Python)
    with pytest.raises(SchemaError, match="model_version"):
        validate_message(
            "ReportVersion", {"model_version": True}, MASTER_SCHEMAS
        )
    validate_message(  # but bool fields still take bools
        "ReportTaskResult",
        {"worker_id": "w", "task_id": 1, "success": True},
        MASTER_SCHEMAS,
    )


def test_malformed_request_fails_at_grpc_boundary():
    servicer = MasterServicer(TaskDispatcher([]))
    server = MasterServer(servicer, port=0).start()
    try:
        client = JsonRpcClient(server.address)
        client.wait_ready(10)
        # client-side validation fires first, in the caller's stack frame
        with pytest.raises(SchemaError, match="worker_id"):
            client.call("GetTask", {})
        # bypass client validation: the SERVER enforces the same schema
        raw = JsonRpcClient(server.address, schemas={})
        with pytest.raises(SchemaError, match="unknown method"):
            raw.call("GetTask", {})  # empty table -> everything unknown
        unchecked = JsonRpcClient(server.address, schemas=None)
        unchecked._schemas = None
        with pytest.raises(grpc.RpcError) as err:
            unchecked.call("GetTask", {"worker_id": 42})
        assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "worker_id" in err.value.details()
        # unknown methods are structured errors, not hangs or crashes
        with pytest.raises(grpc.RpcError) as err:
            unchecked.call("NoSuchMethod", {})
        assert err.value.code() == grpc.StatusCode.UNIMPLEMENTED
        # and a well-formed call still works end to end
        assert client.call("JobStatus", {})["finished"] is True
    finally:
        server.stop()


def test_protocol_version_negotiation():
    """A mismatched wire version is rejected at RegisterWorker — the
    worker's FIRST call — with a structured error naming both versions;
    matching and absent (pre-versioning) versions register fine."""
    from elasticdl_tpu.common.rpc import PROTOCOL_VERSION

    servicer = MasterServicer(TaskDispatcher([]))
    server = MasterServer(servicer, port=0).start()
    try:
        client = JsonRpcClient(server.address)
        client.wait_ready(10)
        ok = client.call(
            "RegisterWorker", {"worker_id": "w-new", "proto": PROTOCOL_VERSION}
        )
        assert "version" in ok
        legacy = client.call("RegisterWorker", {"worker_id": "w-legacy"})
        assert "version" in legacy
        with pytest.raises(grpc.RpcError) as err:
            client.call(
                "RegisterWorker",
                {"worker_id": "w-old", "proto": PROTOCOL_VERSION + 7},
            )
        assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION
        assert f"v{PROTOCOL_VERSION + 7}" in err.value.details()
        assert f"v{PROTOCOL_VERSION}" in err.value.details()
        # the rejected worker never entered the membership
        members = client.call("GetMembership", {})["workers"]
        assert "w-old" not in members and "w-new" in members
    finally:
        server.stop()
