"""graftlint: each pass catches its seeded fixture violation (and passes
the clean twin), waiver syntax is enforced, and the REPO ITSELF lints
clean — tier-1 is the enforcement gate the invariants ride on."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from elasticdl_tpu.analysis import all_passes
from elasticdl_tpu.analysis.blocking import BlockingPropagationPass
from elasticdl_tpu.analysis.collective_shim import CollectiveShimPass
from elasticdl_tpu.analysis.compat_shim import CompatShimPass
from elasticdl_tpu.analysis.core import SourceFile, lint_text, run_lint, run_passes
from elasticdl_tpu.analysis.durability import (
    DurableWriteDisciplinePass,
    RecoveryReadDisciplinePass,
)
from elasticdl_tpu.analysis.hot_path import HotPathSyncPass
from elasticdl_tpu.analysis.import_hygiene import ImportHygienePass, module_dependents
from elasticdl_tpu.analysis.lock_discipline import LockDisciplinePass
from elasticdl_tpu.analysis.lock_order import LockOrderPass
from elasticdl_tpu.analysis.rpc_discipline import RpcDisciplinePass
from elasticdl_tpu.analysis.thread_hygiene import ThreadHygienePass
from elasticdl_tpu.analysis.wire_discipline import (
    WireDisciplinePass,
    WireEvolutionPass,
    wire_fingerprint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(src: str, passes) -> list:
    return lint_text(textwrap.dedent(src), passes)


def _rules(findings) -> set:
    return {f.rule for f in findings}


# ---- lock-discipline ----

LOCK_SEEDED = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0  # guarded-by: _lock

        def bump(self):
            self._count += 1  # race: no lock held
"""

LOCK_CLEAN = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0  # guarded-by: _lock

        def bump(self):
            with self._lock:
                self._count += 1

        def _bump_locked(self):  # guarded-by: _lock
            self._count += 1
"""


def test_lock_discipline_flags_unguarded_touch():
    findings = _lint(LOCK_SEEDED, [LockDisciplinePass()])
    assert len(findings) == 1
    assert findings[0].rule == "lock-discipline"
    assert "_count" in findings[0].message


def test_lock_discipline_clean_twin():
    assert _lint(LOCK_CLEAN, [LockDisciplinePass()]) == []


def test_lock_discipline_closure_does_not_inherit_with_block():
    # A closure runs AFTER the with-block releases the lock: the classic
    # background-thread race must be flagged even though the def sits
    # lexically inside the locked region.
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0  # guarded-by: _lock

            def go(self):
                with self._lock:
                    def bg():
                        self._x += 1
                    t = threading.Thread(target=bg, daemon=True)
                t.start()
    """
    findings = _lint(src, [LockDisciplinePass()])
    assert len(findings) == 1 and "_x" in findings[0].message


# ---- hot-path-sync ----

HOT_SEEDED = """
    import time

    class W:
        # hot-path: the dispatch loop
        def dispatch(self):
            time.sleep(0.1)
"""

HOT_CLEAN = """
    import time

    class W:
        # hot-path: the dispatch loop
        def dispatch(self):
            with self.phases.phase("control"):
                self.master.call("GetTask", {})

        def not_hot(self):
            time.sleep(0.1)
"""


def test_hot_path_flags_sleep():
    findings = _lint(HOT_SEEDED, [HotPathSyncPass()])
    assert _rules(findings) == {"hot-path-sync"}


def test_hot_path_clean_twin_phase_boundary_and_unmarked():
    # Blocking inside a phases.phase(...) boundary is accounted-by-design;
    # unmarked functions are out of scope.
    assert _lint(HOT_CLEAN, [HotPathSyncPass()]) == []


def test_hot_path_device_reads_and_rpc_flagged():
    src = """
        class W:
            # hot-path
            def f(self):
                x = self.metrics.item()
                y = int(self.state.step)
                self.master.call("Report", {})
    """
    findings = _lint(src, [HotPathSyncPass()])
    assert len(findings) == 3


def test_hot_path_except_handler_exempt():
    src = """
        import time

        class W:
            # hot-path
            def f(self):
                try:
                    self.go()
                except Exception:
                    time.sleep(1.0)  # error path: off the hot path
    """
    assert _lint(src, [HotPathSyncPass()]) == []


# ---- blocking-propagation (v2: interprocedural) ----

# The tentpole's motivating hole: the helper wraps block_until_ready, the
# hot-path caller has no primitive of its own.  r7's hot-path-sync is
# provably blind to it; blocking-propagation must fire on the call edge.
BLOCKING_VIA_HELPER = """
    class W:
        def _settle(self):
            self.state.block_until_ready()

        # hot-path
        def dispatch(self):
            self._settle()
"""


def test_blocking_via_helper_missed_by_r7_caught_by_propagation():
    src = textwrap.dedent(BLOCKING_VIA_HELPER)
    assert lint_text(src, [HotPathSyncPass()]) == []  # r7: provably silent
    findings = lint_text(src, [BlockingPropagationPass()])
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "blocking-propagation"
    assert "_settle" in f.message and "block_until_ready" in f.message


def test_blocking_propagation_two_levels_deep_with_witness_chain():
    src = """
        import time

        def _inner():
            time.sleep(1.0)

        def _outer():
            _inner()

        class W:
            # hot-path
            def dispatch(self):
                self._go()

            def _go(self):
                _outer()
    """
    findings = _lint(src, [BlockingPropagationPass()])
    assert len(findings) == 1
    # The witness names every hop down to the primitive.
    msg = findings[0].message
    assert "_go" in msg and "_outer" in msg and "_inner" in msg
    assert "time.sleep" in msg


def test_blocking_propagation_clean_twins():
    # Accounted (phase boundary at the call site OR inside the helper),
    # waived primitives, and error-path calls do not propagate.
    src = """
        import time

        class W:
            def _accounted(self):
                with self.phases.phase("checkpoint"):
                    self.state.block_until_ready()

            def _waived(self):
                # graftlint: allow[hot-path-sync] idle poll is the work here
                time.sleep(0.1)

            def _blocks(self):
                time.sleep(0.1)

            # hot-path
            def dispatch(self):
                self._accounted()
                self._waived()
                with self.phases.phase("control"):
                    self._blocks()
                try:
                    pass
                except Exception:
                    self._blocks()
    """
    assert _lint(src, [BlockingPropagationPass()]) == []


def test_blocking_propagation_waivable_at_call_site():
    src = """
        class W:
            def _settle(self):
                self.state.block_until_ready()

            # hot-path
            def dispatch(self):
                # graftlint: allow[blocking-propagation] startup settle, runs once
                self._settle()
    """
    assert _lint(src, [BlockingPropagationPass()]) == []


# ---- lock-order (v2: interprocedural) ----

LOCK_INVERSION = """
    import threading

    class C:
        def __init__(self):
            self._l1 = threading.Lock()
            self._l2 = threading.Lock()

        def path_a(self):
            with self._l1:
                self._take2()

        def _take2(self):
            with self._l2:
                pass

        def path_b(self):
            with self._l2:
                with self._l1:
                    pass
"""


def test_lock_order_reports_cycle_with_witness_path():
    findings = _lint(LOCK_INVERSION, [LockOrderPass()])
    cycles = [f for f in findings if "potential deadlock" in f.message]
    assert len(cycles) == 1
    msg = cycles[0].message
    # Full witness: both lock names and the file:line of each hop.
    assert "C._l1" in msg and "C._l2" in msg
    assert "path_a" in msg or "_take2" in msg
    assert "path_b" in msg
    assert "fixture.py:" in msg


def test_lock_order_clean_consistent_nesting():
    src = """
        import threading

        class C:
            def __init__(self):
                self._l1 = threading.Lock()
                self._l2 = threading.Lock()

            def path_a(self):
                with self._l1:
                    self._take2()

            def _take2(self):
                with self._l2:
                    pass

            def path_b(self):
                with self._l1:
                    with self._l2:
                        pass
    """
    assert _lint(src, [LockOrderPass()]) == []


def test_lock_order_self_deadlock_through_helper():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self._inner()

            def _inner(self):
                with self._lock:
                    pass
    """
    findings = _lint(src, [LockOrderPass()])
    assert len(findings) == 1
    assert "self-deadlock" in findings[0].message


def test_lock_order_leaf_annotation_enforced():
    src = """
        import threading

        class C:
            def __init__(self):
                self._leaf = threading.Lock()  # lock-order: leaf
                self._other = threading.Lock()

            def bad(self):
                with self._leaf:
                    with self._other:
                        pass
    """
    findings = _lint(src, [LockOrderPass()])
    assert len(findings) == 1
    assert "leaf" in findings[0].message


def test_lock_order_before_annotation_enforced():
    src = """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()  # lock-order: before(_b)
                self._b = threading.Lock()

            def ok(self):
                with self._a:
                    with self._b:
                        pass

            def bad(self):
                with self._b:
                    with self._a:
                        pass
    """
    findings = _lint(src, [LockOrderPass()])
    # The declared-order violation plus the cycle the two paths form.
    assert any("before" in f.message for f in findings)


def test_lock_order_closure_does_not_inherit_held_set():
    # A closure runs later on another thread: the lock held lexically
    # around the def is NOT held when the closure's body acquires.
    src = """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()  # lock-order: leaf
                self._b = threading.Lock()

            def go(self):
                with self._a:
                    def bg():
                        with self._b:
                            pass
                    t = threading.Thread(target=bg, daemon=True)
                t.start()
    """
    assert _lint(src, [LockOrderPass()]) == []


def test_lock_order_locksan_kwargs_must_match_comment():
    src = """
        from elasticdl_tpu.common import locksan

        class C:
            def __init__(self):
                self._a = locksan.lock("C._a", leaf=True)
    """
    findings = _lint(src, [LockOrderPass()])
    assert len(findings) == 1
    assert "disagrees" in findings[0].message
    clean = """
        from elasticdl_tpu.common import locksan

        class C:
            def __init__(self):
                self._a = locksan.lock("C._a", leaf=True)  # lock-order: leaf
    """
    assert _lint(clean, [LockOrderPass()]) == []


def test_lock_order_locksan_name_must_match_attribute():
    src = """
        from elasticdl_tpu.common import locksan

        class C:
            def __init__(self):
                self._a = locksan.lock("C._wrong")
    """
    findings = _lint(src, [LockOrderPass()])
    assert len(findings) == 1 and "does not match" in findings[0].message


def test_lock_order_malformed_annotation_is_finding():
    src = """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()  # lock-order: sideways
    """
    findings = _lint(src, [LockOrderPass()])
    assert len(findings) == 1 and "malformed" in findings[0].message


# ---- stale-waiver ----

def test_stale_waiver_flagged_when_nothing_suppressed():
    src = """
        import time

        class W:
            # hot-path
            def f(self):
                # graftlint: allow[hot-path-sync] this line no longer blocks
                x = 1
                return x
    """
    findings = _lint(src, [HotPathSyncPass()])
    assert _rules(findings) == {"stale-waiver"}
    assert "suppresses no finding" in findings[0].message


def test_live_waiver_not_stale():
    src = """
        import time

        class W:
            # hot-path
            def f(self):
                # graftlint: allow[hot-path-sync] idle poll is the work here
                time.sleep(0.1)
    """
    assert _lint(src, [HotPathSyncPass()]) == []


def test_stale_waiver_only_judged_for_rules_that_ran():
    # A thread-hygiene waiver cannot be judged stale by a run that never
    # executed the thread-hygiene pass.
    src = """
        def f():
            # graftlint: allow[thread-hygiene] joined in caller scope
            pass
    """
    assert _lint(src, [HotPathSyncPass()]) == []
    findings = _lint(src, [ThreadHygienePass()])
    assert _rules(findings) == {"stale-waiver"}


def test_propagation_blocking_waiver_is_not_stale():
    # The waiver on a non-hot helper's primitive is load-bearing: it stops
    # the primitive from propagating to hot callers.  The full suite must
    # neither propagate NOR call the waiver stale.
    src = """
        import time

        class W:
            def _poll(self):
                # graftlint: allow[hot-path-sync] idle poll is the work here
                time.sleep(0.1)

            # hot-path
            def dispatch(self):
                self._poll()
    """
    assert _lint(src, all_passes()) == []


def test_lock_order_condition_is_reentrant():
    # threading.Condition() wraps an RLock: same-thread nested entry (even
    # through a helper) is legal and must not read as a self-deadlock.
    src = """
        import threading

        class C:
            def __init__(self):
                self._cond = threading.Condition()

            def outer(self):
                with self._cond:
                    self._inner()

            def _inner(self):
                with self._cond:
                    pass
    """
    assert _lint(src, [LockOrderPass()]) == []


# ---- --changed dependents ----

def test_module_dependents_transitive_closure():
    srcs = _sources({
        "pkg/__init__.py": "",
        "pkg/helper.py": "x = 1\n",
        "pkg/mid.py": "from pkg.helper import x\n",
        "pkg/root.py": "from pkg.mid import x\n",
        "pkg/unrelated.py": "y = 2\n",
    })
    deps = module_dependents(srcs, {"pkg/helper.py"})
    assert deps == {"pkg/helper.py", "pkg/mid.py", "pkg/root.py"}


def test_module_dependents_changed_package_init():
    # Importing pkg.sub.mod executes pkg/sub/__init__: a changed package
    # __init__ makes every importer underneath it a dependent.
    srcs = _sources({
        "pkg/__init__.py": "",
        "pkg/sub/__init__.py": "",
        "pkg/sub/mod.py": "y = 2\n",
        "pkg/user.py": "from pkg.sub.mod import y\n",
    })
    deps = module_dependents(srcs, {"pkg/sub/__init__.py"})
    assert "pkg/user.py" in deps


# ---- compat-shim ----

SHIM_SEEDED = """
    from jax.experimental.shard_map import shard_map

    def f(mesh):
        return shard_map(lambda x: x, mesh=mesh)
"""

SHIM_CLEAN = """
    from elasticdl_tpu.common.jax_compat import axis_size, shard_map

    def f(mesh):
        return shard_map(lambda x: x, mesh=mesh)
"""


def test_compat_shim_flags_raw_import():
    findings = _lint(SHIM_SEEDED, [CompatShimPass()])
    assert _rules(findings) == {"compat-shim"}


def test_compat_shim_clean_twin():
    assert _lint(SHIM_CLEAN, [CompatShimPass()]) == []


def test_compat_shim_flags_attr_spellings_but_not_in_shim_module():
    src = """
        import jax
        from jax import lax

        def f():
            jax.distributed.initialize(coordinator_address="x")
            return lax.axis_size("dp")
    """
    findings = _lint(src, [CompatShimPass()])
    assert len(findings) == 2
    # The shim module itself is the one place allowed to spell these.
    clean = lint_text(
        textwrap.dedent(src), [CompatShimPass()],
        path="elasticdl_tpu/common/jax_compat.py",
    )
    assert clean == []


# ---- collective-shim (graftreduce r15) ----

COLLECTIVE_SEEDED = """
    from jax import lax

    def local_step(grads, axes):
        loss = lax.psum(grads, axes)
        mean = lax.pmean(grads, axes)
        shard = lax.psum_scatter(grads, "dp", scatter_dimension=0, tiled=True)
        return loss, mean, shard
"""

COLLECTIVE_CLEAN = """
    from jax import lax
    from elasticdl_tpu.parallel import collectives as coll

    def local_step(grads, axes, topo):
        loss = coll.psum(grads, axes, topo)
        mean = coll.pmean(grads, axes, topo)
        shard = coll.psum_scatter(grads, "dp", scatter_dimension=0, tiled=True)
        gathered = lax.all_gather(grads, "dp")  # moves data, not a reduction
        return loss, mean, shard, gathered
"""


def test_collective_shim_flags_raw_reductions():
    findings = _lint(COLLECTIVE_SEEDED, [CollectiveShimPass()])
    assert _rules(findings) == {"collective-shim"}
    assert len(findings) == 3  # psum + pmean + psum_scatter


def test_collective_shim_clean_twin():
    assert _lint(COLLECTIVE_CLEAN, [CollectiveShimPass()]) == []


def test_collective_shim_flags_import_alias():
    # ``from jax.lax import psum`` would smuggle the raw spelling past
    # the attribute check — the import itself is the finding.
    src = """
        from jax.lax import psum, all_gather

        def f(x):
            return psum(x, "dp"), all_gather(x, "dp")
    """
    findings = _lint(src, [CollectiveShimPass()])
    assert len(findings) == 1  # all_gather stays legal


def test_collective_shim_exempts_shim_modules():
    src = textwrap.dedent(COLLECTIVE_SEEDED)
    for path in (
        "elasticdl_tpu/parallel/collectives.py",
        "elasticdl_tpu/common/jax_compat.py",
    ):
        assert lint_text(src, [CollectiveShimPass()], path=path) == []


def test_collective_shim_jax_lax_spelling():
    src = """
        import jax

        def f(x):
            return jax.lax.psum(x, "dp")
    """
    findings = _lint(src, [CollectiveShimPass()])
    assert _rules(findings) == {"collective-shim"}


# ---- rpc-discipline ----

RPC_SEEDED = """
    class Store:
        def probe(self):
            return self._client.call("Stats", {})
"""

RPC_CLEAN = """
    class Store:
        def probe(self):
            return self._client.call("Stats", {}, timeout_s=5.0)

        def _retry(self, fn):
            return fn()

        def pull(self):
            return self._retry(lambda: self._client.call("Pull", {}))

        def inside_wrapper(self):
            # wrapper functions own deadline+backoff for their bodies
            pass

        def via_master(self):
            return self.master.call("GetTask", {})  # proxy owns the deadline

        def not_rpc(self):
            import subprocess
            return subprocess.call(["true"])
"""


def test_rpc_discipline_flags_bare_stub_call():
    findings = _lint(RPC_SEEDED, [RpcDisciplinePass()])
    assert _rules(findings) == {"rpc-discipline"}


def test_rpc_discipline_clean_twin():
    assert _lint(RPC_CLEAN, [RpcDisciplinePass()]) == []


# r18: bare readiness waits — the raw channel_ready_future primitive is a
# hand-rolled reconnect loop (one hard timeout, no retry accounting, no
# jitter) and is legal only inside common/rpc.py, whose
# wait_channel_ready wraps it in the shared backoff helper.

READY_SEEDED = """
    import grpc

    class Client:
        def wait_ready(self, timeout_s=10.0):
            grpc.channel_ready_future(self._channel).result(timeout=timeout_s)
"""

READY_CLEAN = """
    from elasticdl_tpu.common.rpc import wait_channel_ready

    class Client:
        def wait_ready(self, timeout_s=10.0):
            wait_channel_ready(
                self._channel, service="x", budget_s=timeout_s
            )
"""


def test_rpc_discipline_flags_bare_readiness_wait():
    findings = _lint(READY_SEEDED, [RpcDisciplinePass()])
    assert _rules(findings) == {"rpc-discipline"}
    assert "channel_ready_future" in findings[0].message


def test_rpc_discipline_readiness_clean_twin():
    assert _lint(READY_CLEAN, [RpcDisciplinePass()]) == []


def test_rpc_discipline_readiness_owner_module_exempt():
    src = textwrap.dedent(READY_SEEDED)
    assert lint_text(
        src, [RpcDisciplinePass()],
        path="elasticdl_tpu/common/rpc.py",
    ) == []


# ---- thread-hygiene ----

THREAD_SEEDED = """
    import threading

    def leak():
        threading.Thread(target=print).start()
"""

THREAD_CLEAN = """
    import threading

    def daemonized():
        threading.Thread(target=print, daemon=True).start()

    def joined():
        ts = [threading.Thread(target=print) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
"""


def test_thread_hygiene_flags_leaked_thread():
    findings = _lint(THREAD_SEEDED, [ThreadHygienePass()])
    assert _rules(findings) == {"thread-hygiene"}


def test_thread_hygiene_clean_twin():
    assert _lint(THREAD_CLEAN, [ThreadHygienePass()]) == []


# ---- import-hygiene ----

def _sources(files: dict) -> list:
    return [
        SourceFile(path, textwrap.dedent(text)) for path, text in files.items()
    ]


def test_import_hygiene_flags_transitive_jax():
    srcs = _sources({
        "pkg/__init__.py": "",
        "pkg/control.py": "from pkg.helper import x\n",
        "pkg/helper.py": "import jax\nx = 1\n",
    })
    p = ImportHygienePass(roots=("pkg.control",))
    findings = run_passes(srcs, [p])
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "import-hygiene" and f.path == "pkg/control.py"
    assert "pkg.helper" in f.message and f.line == 1


def test_import_hygiene_deferred_import_is_clean():
    srcs = _sources({
        "pkg/__init__.py": "",
        "pkg/control.py": "from pkg.helper import x\n",
        "pkg/helper.py": "def f():\n    import jax\n    return jax\nx = 1\n",
    })
    findings = run_passes(srcs, [ImportHygienePass(roots=("pkg.control",))])
    assert findings == []


def test_import_hygiene_counts_package_init():
    # Importing pkg.sub.mod executes pkg/__init__ and pkg/sub/__init__ —
    # a jax import hiding in an ancestor package must be caught.
    srcs = _sources({
        "pkg/__init__.py": "",
        "pkg/root.py": "from pkg.sub.mod import y\n",
        "pkg/sub/__init__.py": "import jax\n",
        "pkg/sub/mod.py": "y = 2\n",
    })
    findings = run_passes(srcs, [ImportHygienePass(roots=("pkg.root",))])
    assert len(findings) == 1


def test_import_hygiene_flags_module_level_platform_call():
    # The real leak this pass closed: apply_platform_env() imports jax
    # inside its body, so a module-level CALL executes the import even
    # though no 'import jax' statement is visible at module scope.
    srcs = _sources({
        "pkg/__init__.py": "",
        "pkg/control.py": (
            "from elasticdl_tpu.common.platform import apply_platform_env\n"
            "apply_platform_env()\n"
        ),
    })
    findings = run_passes(srcs, [ImportHygienePass(roots=("pkg.control",))])
    assert len(findings) == 1 and findings[0].line == 2


def test_master_process_is_jax_free_at_runtime():
    # The runtime twin of the static pass: importing the master stack in a
    # fresh interpreter must not pull jax into the process.
    code = (
        "import sys; "
        "import elasticdl_tpu.master.main, elasticdl_tpu.master.servicer, "
        "elasticdl_tpu.master.pod_manager, elasticdl_tpu.common.platform; "
        "sys.exit(1 if 'jax' in sys.modules else 0)"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, timeout=120,
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr[-2000:]


# ---- waivers ----

def test_valid_waiver_suppresses_finding():
    src = """
        import time

        class W:
            # hot-path
            def f(self):
                # graftlint: allow[hot-path-sync] idle poll is the work here
                time.sleep(0.1)
    """
    assert _lint(src, [HotPathSyncPass()]) == []


def test_waiver_same_line_form():
    src = """
        import time

        class W:
            # hot-path
            def f(self):
                time.sleep(0.1)  # graftlint: allow[hot-path-sync] idle poll
    """
    assert _lint(src, [HotPathSyncPass()]) == []


def test_waiver_wrong_rule_does_not_suppress():
    src = """
        import time

        class W:
            # hot-path
            def f(self):
                # graftlint: allow[thread-hygiene] reason for another rule
                time.sleep(0.1)
    """
    findings = _lint(src, [HotPathSyncPass()])
    assert _rules(findings) == {"hot-path-sync"}


@pytest.mark.parametrize(
    "waiver, expect",
    [
        ("# graftlint: allow[hot-path-sync]", "no reason"),
        ("# graftlint: allow[] why not", "names no rule"),
        ("# graftlint: allow hot-path-sync why", "malformed"),
        ("# graftlint: allow[not-a-rule] why", "unknown rule"),
    ],
)
def test_malformed_waivers_are_findings(waiver, expect):
    src = f"""
        def f():
            {waiver}
            pass
    """
    findings = _lint(src, [])
    assert len(findings) == 1
    assert findings[0].rule == "waiver-syntax"
    assert expect in findings[0].message


def test_malformed_waiver_cannot_waive_itself():
    src = """
        def f():
            # graftlint: allow[waiver-syntax] trying to excuse myself
            # graftlint: allow[]
            pass
    """
    findings = _lint(src, [])
    assert any("names no rule" in f.message for f in findings)


def test_import_hygiene_module_level_loop_body_counts():
    # A top-level loop body executes at import time too — it must not be
    # a smuggling route.
    srcs = _sources({
        "pkg/__init__.py": "",
        "pkg/control.py": "for _ in range(1):\n    import jax\n",
    })
    findings = run_passes(srcs, [ImportHygienePass(roots=("pkg.control",))])
    assert len(findings) == 1


# ---- parse errors and scoping ----

def test_parse_error_has_its_own_rule(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    findings = run_lint([str(tmp_path)])
    assert [f.rule for f in findings] == ["parse-error"]


def test_only_paths_scopes_parse_errors_too(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    (tmp_path / "ok.py").write_text("x = 1\n")
    findings = run_lint(
        [str(tmp_path)], rel_to=str(tmp_path), only_paths={"ok.py"}
    )
    assert findings == []


# ---- trace-discipline ----

TRACE_SEEDED = """
    from elasticdl_tpu.common import trace

    class Worker:
        # hot-path: the steady-state task loop
        def poll(self):
            rec = trace.default()
            rec.instant("tick", cat="loop")
            return rec.drain_slice(512)  # export from the hot path: finding
"""

TRACE_CLEAN = """
    from elasticdl_tpu.common import trace

    class Worker:
        # hot-path: the steady-state task loop
        def poll(self):
            with trace.span("poll", cat="loop"):
                trace.instant("tick", cat="loop")

        def ship(self):
            # Not hot-path: draining from a control-plane boundary is the
            # intended pattern.
            return trace.default().drain_slice(512)
"""


def test_trace_discipline_seeded_and_clean():
    from elasticdl_tpu.analysis.trace_discipline import TraceDisciplinePass

    findings = _lint(TRACE_SEEDED, [TraceDisciplinePass()])
    assert _rules(findings) == {"trace-discipline"}
    assert len(findings) == 1
    assert _lint(TRACE_CLEAN, [TraceDisciplinePass()]) == []


def test_trace_discipline_flags_export_and_chrome_events():
    from elasticdl_tpu.analysis.trace_discipline import TraceDisciplinePass

    src = """
        class W:
            # hot-path
            def step(self, rec):
                rec.export()
                rec.chrome_events()
    """
    findings = _lint(src, [TraceDisciplinePass()])
    assert len(findings) == 2


def test_trace_discipline_ignores_unrelated_export():
    from elasticdl_tpu.analysis.trace_discipline import TraceDisciplinePass

    src = """
        class W:
            # hot-path
            def step(self, model):
                model.export()  # not a trace recorder: no finding
    """
    assert _lint(src, [TraceDisciplinePass()]) == []


def test_trace_discipline_waivable_and_exempts_error_paths():
    from elasticdl_tpu.analysis.trace_discipline import TraceDisciplinePass

    src = """
        from elasticdl_tpu.common import trace

        class W:
            # hot-path
            def step(self, rec):
                # graftlint: allow[trace-discipline] deliberate debug drain
                rec.drain_slice(8)
                try:
                    pass
                except Exception:
                    rec.drain_slice(8)  # error path: exempt
    """
    assert _lint(src, [TraceDisciplinePass()]) == []


# ---- chaos-discipline ----

CHAOS_SEEDED = """
    from elasticdl_tpu import chaos

    class Worker:
        # hot-path: the steady-state task loop
        def poll(self):
            chaos.hook("worker:task", rank=0, step=1)
            chaos.configure("stall:ms=5")  # plan mutation on the hot path: finding
"""

CHAOS_CLEAN = """
    from elasticdl_tpu import chaos

    class Worker:
        def __init__(self, config):
            # Arming at a process boundary is the intended pattern.
            chaos.configure(config.chaos)
            chaos.set_context(rank=0)

        # hot-path: the steady-state task loop
        def poll(self):
            chaos.hook("worker:task", rank=0, step=1)
"""


def test_chaos_discipline_seeded_and_clean():
    from elasticdl_tpu.analysis.chaos_discipline import ChaosDisciplinePass

    findings = _lint(CHAOS_SEEDED, [ChaosDisciplinePass()])
    assert _rules(findings) == {"chaos-discipline"}
    assert len(findings) == 1
    assert _lint(CHAOS_CLEAN, [ChaosDisciplinePass()]) == []


def test_chaos_discipline_flags_fire_set_context_and_construction():
    from elasticdl_tpu.analysis.chaos_discipline import ChaosDisciplinePass

    src = """
        class W:
            # hot-path
            def step(self, chaos, inj):
                chaos.default().fire("worker:task", {})
                inj.set_context(rank=1)
                ChaosInjector()
    """
    findings = _lint(src, [ChaosDisciplinePass()])
    assert len(findings) == 3


def test_chaos_discipline_ignores_unrelated_receivers():
    from elasticdl_tpu.analysis.chaos_discipline import ChaosDisciplinePass

    src = """
        class W:
            # hot-path
            def step(self, model, logger):
                model.configure(lr=0.1)   # not a chaos receiver
                logger.fire("event")      # nor this
    """
    assert _lint(src, [ChaosDisciplinePass()]) == []


def test_chaos_discipline_waivable_and_exempts_error_paths():
    from elasticdl_tpu.analysis.chaos_discipline import ChaosDisciplinePass

    src = """
        from elasticdl_tpu import chaos

        class W:
            # hot-path
            def step(self):
                # graftlint: allow[chaos-discipline] deliberate hot rearm in a test harness
                chaos.configure("stall:ms=1")
                try:
                    pass
                except Exception:
                    chaos.configure("")  # error path: exempt
    """
    assert _lint(src, [ChaosDisciplinePass()]) == []


# ---- gauge-discipline ----

GAUGE_SEEDED = """
    from elasticdl_tpu.common import gauge

    class Worker:
        def __init__(self):
            self.gauges = gauge.Registry()
            self._g_examples = self.gauges.counter("edl_examples_trained_total")

        # hot-path: the steady-state task loop
        def step(self):
            self._g_examples.inc(64)
            return self.gauges.snapshot()  # scrape from the hot path: finding
"""

GAUGE_CLEAN = """
    from elasticdl_tpu.common import gauge

    class Worker:
        def __init__(self):
            self.gauges = gauge.Registry()
            self._g_examples = self.gauges.counter("edl_examples_trained_total")
            self._g_step_ms = self.gauges.histogram("edl_step_ms")

        # hot-path: the steady-state task loop
        def step(self):
            # O(1) ring/counter API: the only gauge calls legal here.
            self._g_examples.inc(64)
            self._g_step_ms.observe(8.2)
            self.gauges.gauge("edl_lease_depth").set(3)

        def gauge_payload(self):
            # Not hot-path: snapshotting at a control-plane boundary is
            # the intended pattern.
            return {"families": self.gauges.snapshot()}
"""


def test_gauge_discipline_seeded_and_clean():
    from elasticdl_tpu.analysis.gauge_discipline import GaugeDisciplinePass

    findings = _lint(GAUGE_SEEDED, [GaugeDisciplinePass()])
    assert _rules(findings) == {"gauge-discipline"}
    assert len(findings) == 1
    assert _lint(GAUGE_CLEAN, [GaugeDisciplinePass()]) == []


def test_gauge_discipline_flags_render_and_aggregation_calls():
    from elasticdl_tpu.analysis.gauge_discipline import GaugeDisciplinePass

    src = """
        class W:
            # hot-path
            def step(self, reg, fleet):
                reg.render_prometheus()
                fleet.fleet_snapshot()
                reg.scalar_values(["edl_examples_trained_total"])
    """
    assert len(_lint(src, [GaugeDisciplinePass()])) == 3


def test_gauge_discipline_ignores_unrelated_snapshot():
    from elasticdl_tpu.analysis.gauge_discipline import GaugeDisciplinePass

    src = """
        class W:
            # hot-path
            def step(self):
                # PhaseTimers/trainer snapshots are not gauge scrapes.
                self.phases.snapshot()
                self.trainer.snapshot_state()
    """
    assert _lint(src, [GaugeDisciplinePass()]) == []


def test_gauge_discipline_waivable_and_exempts_error_paths():
    from elasticdl_tpu.analysis.gauge_discipline import GaugeDisciplinePass

    src = """
        class W:
            # hot-path
            def step(self, reg):
                # graftlint: allow[gauge-discipline] deliberate debug scrape
                reg.render_prometheus()
                try:
                    pass
                except Exception:
                    reg.render_prometheus()  # error path: exempt
    """
    assert _lint(src, [GaugeDisciplinePass()]) == []


# ---- the repo-wide gate ----

def test_repo_lints_clean():
    findings = run_lint(
        [os.path.join(REPO, "elasticdl_tpu"), os.path.join(REPO, "tools")],
        rel_to=REPO,
    )
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_exits_zero_on_repo_and_one_on_violation(tmp_path):
    out = subprocess.run(
        [sys.executable, "tools/graftlint.py", "elasticdl_tpu", "tools"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import threading\n"
        "threading.Thread(target=print).start()\n"
    )
    out = subprocess.run(
        [sys.executable, "tools/graftlint.py", str(bad)],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 1
    assert "thread-hygiene" in out.stdout


def test_cli_artifact_stamps_counts_and_code_rev(tmp_path):
    art = tmp_path / "LINT_test.json"
    out = subprocess.run(
        [
            sys.executable, "tools/graftlint.py", "elasticdl_tpu", "tools",
            "--artifact", str(art),
        ],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    rec = json.loads(art.read_text())
    assert rec["findings"] == 0
    assert rec["files_scanned"] > 50
    assert "code_rev" in rec and "rules" in rec
    assert "command" in rec  # write_artifact's shared stamp


def test_cli_json_includes_waiver_inventory():
    out = subprocess.run(
        [sys.executable, "tools/graftlint.py", "elasticdl_tpu", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert set(doc) == {"findings", "waivers"}
    assert doc["findings"] == []
    # The repo carries reasoned waivers; each inventory entry is complete.
    assert len(doc["waivers"]) > 0
    for w in doc["waivers"]:
        assert set(w) == {"path", "line", "rule", "reason"}
        assert w["reason"]


def test_cli_artifact_has_lock_graph_and_blocking_roots(tmp_path):
    art = tmp_path / "LINT_test.json"
    out = subprocess.run(
        [
            sys.executable, "tools/graftlint.py", "elasticdl_tpu", "tools",
            "--artifact", str(art),
        ],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    rec = json.loads(art.read_text())
    assert rec["blocking_roots"]["count"] > 0
    assert rec["lock_graph"]["locks"] > 10
    assert rec["lock_graph"]["locksan_wrapped"] > 10
    # The one statically visible nesting: GetGroupTask -> GetTask.
    assert [
        "elasticdl_tpu.master.servicer:MasterServicer._group_lock",
        "elasticdl_tpu.master.servicer:MasterServicer._lock",
    ] in rec["lock_graph"]["edges"]
    assert "Worker._ckpt_lock" in " ".join(rec["lock_graph"]["leaf"])
    assert rec["waivers"] == len(
        [None] * sum(rec["waivers_by_rule"].values())
    )


def test_cli_callgraph_dump():
    out = subprocess.run(
        [sys.executable, "tools/graftlint.py", "elasticdl_tpu", "--callgraph"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["functions"] > 100
    assert any("Worker.run" in q for q in doc["hot_path_functions"])
    assert "elasticdl_tpu.worker.worker:Worker._ckpt_lock" in doc["locks"]
    assert doc["locks"]["elasticdl_tpu.worker.worker:Worker._ckpt_lock"]["leaf"]


def test_cli_changed_fails_loud_when_git_unreadable():
    # 'git broke' must never be reported as 'nothing changed, gate clean'.
    out = subprocess.run(
        [sys.executable, "tools/graftlint.py", "--changed"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env={**os.environ, "GIT_DIR": "/nonexistent"},
    )
    assert out.returncode == 2
    assert "git" in out.stderr


def test_cli_changed_mode_runs(tmp_path):
    # --changed must run and exit cleanly whatever the current diff is;
    # findings it reports are restricted to changed files.
    out = subprocess.run(
        [sys.executable, "tools/graftlint.py", "--changed", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode in (0, 1), out.stderr
    json.loads(out.stdout)  # valid JSON either way


# ---- thread-hygiene v5: Timer + executor shapes ----

def test_timer_seeded_and_clean_twins():
    seeded = """
        import threading

        def fire():
            threading.Timer(5.0, print).start()
    """
    findings = _lint(seeded, [ThreadHygienePass()])
    assert _rules(findings) == {"thread-hygiene"}
    assert "Timer" in findings[0].message
    clean = """
        import threading

        def daemonized():
            t = threading.Timer(5.0, print)
            t.daemon = True
            t.start()

        def cancelled():
            t = threading.Timer(5.0, print)
            t.start()
            t.cancel()

        def joined():
            t = threading.Timer(0.0, print)
            t.start()
            t.join()
    """
    assert _lint(clean, [ThreadHygienePass()]) == []


def test_executor_seeded_and_clean_twins():
    seeded = """
        from concurrent.futures import ThreadPoolExecutor

        def leak():
            pool = ThreadPoolExecutor(4)
            pool.submit(print)
    """
    findings = _lint(seeded, [ThreadHygienePass()])
    assert _rules(findings) == {"thread-hygiene"}
    assert "executor" in findings[0].message
    clean = """
        from concurrent.futures import ThreadPoolExecutor, futures

        class Owner:
            def __init__(self, par):
                # Conditional construction still counts as owned.
                self._pool = ThreadPoolExecutor(4) if par else None

        def handed_to_owner(grpc):
            return grpc.server(ThreadPoolExecutor(8))

        def scoped():
            with ThreadPoolExecutor(2) as pool:
                pool.submit(print)

        def shut_down():
            pool = ThreadPoolExecutor(2)
            pool.submit(print)
            pool.shutdown()
    """
    assert _lint(clean, [ThreadHygienePass()]) == []


# ---- thread-map (v5) ----

def _tmap(files: dict):
    from elasticdl_tpu.analysis.thread_map import shared_thread_map

    return shared_thread_map(_sources(files))


THREADED_MODULE = """
    import threading
    from concurrent.futures import ThreadPoolExecutor

    class W:
        def __init__(self):
            self._pool = ThreadPoolExecutor(2)

        def start(self):
            threading.Thread(
                target=self._watch, name="watcher", daemon=True
            ).start()
            threading.Timer(1.0, self._retry).start()
            fut = self._pool.submit(self._prep, 1)
            fut.add_done_callback(self._done)

        def _watch(self):
            self._tick()

        def _tick(self):
            pass

        def _retry(self):
            pass

        def _prep(self, n):
            pass

        def _done(self, fut):
            pass

        def loop(self):
            pass


    def main():
        w = W()
        w.loop()
"""


def test_thread_map_infers_spawn_shapes_and_propagates():
    tmap = _tmap({"pkg/__init__.py": "", "pkg/mod.py": THREADED_MODULE})
    roles = {
        q.split(":")[-1]: sorted(r) for q, r in tmap.roles.items()
    }
    assert roles["W._watch"] == ["thread:watcher"]
    # Propagated over the call edge, not just the entry.
    assert roles["W._tick"] == ["thread:watcher"]
    assert roles["W._retry"] == ["timer:_retry"]
    assert roles["W._prep"] == ["pool:_prep"]
    assert roles["W._done"] == ["callback:_done"]
    # Constructor-typed local: main's `w = W(); w.loop()` edges into W.loop.
    assert roles["W.loop"] == ["main"]
    # start() itself has no inferred role (nothing spawns INTO it).
    assert "W.start" not in roles


def test_thread_map_closure_target_and_inheritance():
    tmap = _tmap({"pkg/__init__.py": "", "pkg/mod.py": """
        import threading

        def main():
            def bg():
                helper()

            def inline():
                helper2()

            threading.Thread(target=bg, daemon=True).start()
            inline()

        def helper():
            pass

        def helper2():
            pass
    """})
    by_fn = {q.split(":")[-1]: sorted(r) for q, r in tmap.roles.items()}
    # The spawned closure runs ONLY on its thread; calls propagate.
    assert by_fn["helper"] == ["thread:bg"]
    # A non-spawned closure inherits the enclosing function's role.
    assert by_fn["helper2"] == ["main"]


def test_thread_map_grpc_method_table_and_dict_literal():
    tmap = _tmap({"pkg/__init__.py": "", "pkg/svc.py": """
        import grpc

        class FooServicer:
            def method_table(self):
                return {name: getattr(self, name) for name in ("GetTask",)}

            def GetTask(self, req):
                return self._inner()

            def _inner(self):
                pass

        class Shard:
            def __init__(self):
                self._server = grpc.server(None)
                self._server.add_generic_rpc_handlers(())

            def _make(self):
                return {"Pull": self._pull}

            def _pull(self, req):
                pass
    """})
    by_fn = {q.split(":")[-1]: sorted(r) for q, r in tmap.roles.items()}
    assert by_fn["FooServicer.GetTask"] == ["grpc:FooServicer"]
    assert by_fn["FooServicer._inner"] == ["grpc:FooServicer"]
    assert by_fn["Shard._pull"] == ["grpc:Shard"]
    # An ordinary dispatch table in a non-grpc class is NOT an entry.
    tmap2 = _tmap({"pkg/__init__.py": "", "pkg/plain.py": """
        class Plain:
            def table(self):
                return {"a": self._a}

            def _a(self):
                pass
    """})
    assert not any("grpc" in r for rs in tmap2.roles.values() for r in rs)


def test_thread_role_annotation_seeds_and_malformed_is_finding():
    from elasticdl_tpu.analysis.shared_state import SharedStatePass

    tmap = _tmap({"pkg/__init__.py": "", "pkg/mod.py": """
        class W:
            # thread-role: thread:beat — reached through a holder dict
            def tick(self):
                pass
    """})
    by_fn = {q.split(":")[-1]: sorted(r) for q, r in tmap.roles.items()}
    assert by_fn["W.tick"] == ["thread:beat"]
    findings = _lint("""
        class W:
            # thread-role: !!nope
            def tick(self):
                pass
    """, [SharedStatePass()])
    assert _rules(findings) == {"shared-state"}
    assert "malformed thread-role" in findings[0].message


# ---- shared-state (v5) ----

SHARED_SEEDED = """
    import threading

    class W:
        def __init__(self):
            self._depth = 0

        def run(self):
            self._depth = 1

        def start(self):
            threading.Thread(target=self._bg, daemon=True).start()

        def _bg(self):
            self._depth = 2


    def main():
        w = W()
        w.run()
"""

SHARED_CLEAN = """
    import threading

    class W:
        def __init__(self):
            self._lock = threading.Lock()
            self._depth = 0

        def run(self):
            with self._lock:
                self._depth = 1

        def start(self):
            threading.Thread(target=self._bg, daemon=True).start()

        def _bg(self):
            with self._lock:
                self._depth = 2


    def main():
        w = W()
        w.run()
"""


def test_shared_state_cross_role_unguarded_write():
    from elasticdl_tpu.analysis.shared_state import SharedStatePass

    findings = _lint(SHARED_SEEDED, [SharedStatePass()])
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "shared-state"
    assert "_depth" in f.message and "thread:_bg" in f.message
    assert "main" in f.message


def test_shared_state_clean_twin_common_lock():
    from elasticdl_tpu.analysis.shared_state import SharedStatePass

    assert _lint(SHARED_CLEAN, [SharedStatePass()]) == []


def test_shared_state_guarded_by_helper_annotation_counts_as_held():
    # The *_locked helper convention: a def-line '# guarded-by: <lock>'
    # marks the lock held by contract, so the helper's sites share it.
    from elasticdl_tpu.analysis.shared_state import SharedStatePass

    src = """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._depth = 0

            def run(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):  # guarded-by: _lock
                self._depth = 1

            def start(self):
                threading.Thread(target=self._bg, daemon=True).start()

            def _bg(self):
                with self._lock:
                    self._depth = 2


        def main():
            w = W()
            w.run()
    """
    assert _lint(src, [SharedStatePass()]) == []


def test_shared_state_init_and_roleless_sites_exempt():
    from elasticdl_tpu.analysis.shared_state import SharedStatePass

    src = """
        import threading

        class W:
            def __init__(self):
                self._cfg = {}

            def helper_nobody_calls(self):
                self._cfg = {"x": 1}

            def start(self):
                threading.Thread(target=self._bg, daemon=True).start()

            def _bg(self):
                print(self._cfg)
    """
    # The only roled site is the _bg read; writes are __init__ (exempt)
    # and an unreachable helper (unknown role): no finding.
    assert _lint(src, [SharedStatePass()]) == []


def test_shared_state_single_writer_declared_and_violated():
    from elasticdl_tpu.analysis.shared_state import SharedStatePass

    clean = """
        import threading

        class W:
            def __init__(self):
                self._step = 0  # single-writer: main

            def run(self):
                self._step += 1

            def start(self):
                threading.Thread(target=self._bg, daemon=True).start()

            def _bg(self):
                print(self._step)


        def main():
            w = W()
            w.run()
    """
    assert _lint(clean, [SharedStatePass()]) == []
    violated = clean.replace(
        "def _bg(self):\n                print(self._step)",
        "def _bg(self):\n                self._step = 9",
    )
    findings = _lint(violated, [SharedStatePass()])
    assert len(findings) == 1
    assert "single-writer" in findings[0].message
    assert "thread:_bg" in findings[0].message


def test_shared_state_single_writer_unknown_role_is_finding():
    from elasticdl_tpu.analysis.shared_state import SharedStatePass

    src = """
        class W:
            def __init__(self):
                self._step = 0  # single-writer: thread:nope
    """
    findings = _lint(src, [SharedStatePass()])
    assert len(findings) == 1
    assert "unknown role" in findings[0].message


def test_shared_state_gil_atomic_and_rmw_violation():
    from elasticdl_tpu.analysis.shared_state import SharedStatePass

    clean = """
        import threading

        class W:
            def __init__(self):
                self._last = 0.0  # gil-atomic

            def run(self):
                self._last = 1.0

            def start(self):
                threading.Thread(target=self._bg, daemon=True).start()

            def _bg(self):
                self._last = 2.0


        def main():
            w = W()
            w.run()
    """
    assert _lint(clean, [SharedStatePass()]) == []
    violated = clean.replace("self._last = 2.0", "self._last += 2.0")
    findings = _lint(violated, [SharedStatePass()])
    assert len(findings) == 1
    assert "read-modify-write" in findings[0].message


def test_shared_state_waivable_with_reason():
    from elasticdl_tpu.analysis.shared_state import SharedStatePass

    src = SHARED_SEEDED.replace(
        "        def run(self):\n            self._depth = 1",
        "        def run(self):\n"
        "            # graftlint: allow[shared-state] benign telemetry value;"
        " a torn read costs one stale sample\n"
        "            self._depth = 1",
    )
    assert _lint(src, [SharedStatePass()]) == []


def test_shared_state_full_suite_keeps_waiver_live():
    # The waiver must neither be bypassed nor flagged stale by the full
    # pass suite (the r7/r8 adoption pattern).
    src = SHARED_SEEDED.replace(
        "        def run(self):\n            self._depth = 1",
        "        def run(self):\n"
        "            # graftlint: allow[shared-state] benign telemetry value;"
        " a torn read costs one stale sample\n"
        "            self._depth = 1",
    )
    assert _lint(src, all_passes()) == []


# ---- --threadmap CLI ----

def test_cli_threadmap_dump():
    out = subprocess.run(
        [sys.executable, "tools/graftlint.py", "elasticdl_tpu", "--threadmap"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["functions_with_role"] > 100
    assert "grpc:MasterServicer" in doc["roles"]
    assert any(
        "Worker._prep_fused_host" in q
        for q in doc["roles"].get("pool:_prep_fused_host", [])
    )
    assert "thread:heartbeat" in doc["roles"]
    kinds = {e["kind"] for e in doc["entries"]}
    assert {"thread", "timer", "pool", "grpc", "main", "annotation"} <= kinds


def test_cli_artifact_has_thread_map_stats(tmp_path):
    art = tmp_path / "LINT_test.json"
    out = subprocess.run(
        [
            sys.executable, "tools/graftlint.py", "elasticdl_tpu", "tools",
            "--artifact", str(art),
        ],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    rec = json.loads(art.read_text())
    assert rec["metric"] == "lint_findings"
    tm = rec["thread_map"]
    assert tm["roles"] > 10 and tm["entries"] > 20
    assert 0 < tm["functions_with_role"] <= tm["functions_total"]
    assert tm["entries_by_kind"]["grpc"] >= 15
    assert "shared-state" in rec["rules"]


def test_shared_state_container_mutation_is_a_write():
    # self._counts[k] += 1 mutates the SHARED CONTAINER through the
    # attribute — the _known_workers-style check-and-set must flag even
    # though no attribute rebind ever happens.
    from elasticdl_tpu.analysis.shared_state import SharedStatePass

    src = """
        import threading

        class W:
            def __init__(self):
                self._counts = {}

            def run(self):
                self._counts["k"] = self._counts.get("k", 0) + 1

            def start(self):
                threading.Thread(target=self._bg, daemon=True).start()

            def _bg(self):
                self._counts["k"] = 0


        def main():
            w = W()
            w.run()
    """
    findings = _lint(src, [SharedStatePass()])
    assert len(findings) == 1 and "_counts" in findings[0].message
    # And an augmented item assignment is a read-modify-write: illegal
    # under gil-atomic.
    aug = src.replace(
        'self._counts["k"] = self._counts.get("k", 0) + 1',
        'self._counts["k"] += 1',
    ).replace(
        "self._counts = {}",
        "self._counts = {}  # gil-atomic",
    )
    findings = _lint(aug, [SharedStatePass()])
    assert len(findings) == 1
    assert "read-modify-write" in findings[0].message


def test_shared_state_same_role_unlocked_read_not_flagged():
    # The writer role's own unlocked read cannot race writes it is
    # sequenced with: the judgement is per cross-role PAIR, not a global
    # all-site lock intersection.
    from elasticdl_tpu.analysis.shared_state import SharedStatePass

    src = """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._depth = 0

            def run(self):
                with self._lock:
                    self._depth = 1
                print(self._depth)  # same role as the sole writer: safe

            def start(self):
                threading.Thread(target=self._bg, daemon=True).start()

            def _bg(self):
                with self._lock:
                    print(self._depth)


        def main():
            w = W()
            w.run()
    """
    assert _lint(src, [SharedStatePass()]) == []


# ---- jit-shim (v6) ----

JIT_SHIM_SEEDED = """
    import jax
    from jax import jit
    from elasticdl_tpu.common.jax_compat import jit_compiled

    def build(fn):
        return jax.jit(fn)

    def build_shimmed(fn):
        return jit_compiled(fn)
"""

JIT_SHIM_CLEAN = """
    from elasticdl_tpu.common.jax_compat import jit_compiled, jit_donating

    def build(fn):
        return jit_compiled(fn, name="mod.step", expected_variants=1)

    def build_donating(fn):
        return jit_donating(fn, name="mod.train", expected_variants=2)
"""


def test_jit_shim_seeded_and_clean():
    from elasticdl_tpu.analysis.jit_discipline import JitShimPass

    findings = _lint(JIT_SHIM_SEEDED, [JitShimPass()])
    msgs = [f.message for f in findings]
    assert _rules(findings) == {"jit-shim"}
    assert len(findings) == 3  # raw attr, raw import alias, missing name=
    assert any("from jax import jit" in m for m in msgs)
    assert any("raw jax.jit" in m for m in msgs)
    assert any("declares no name=" in m for m in msgs)
    assert _lint(JIT_SHIM_CLEAN, [JitShimPass()]) == []


def test_jit_shim_exempts_the_shim_module():
    from elasticdl_tpu.analysis.jit_discipline import JitShimPass
    import textwrap

    src = SourceFile(
        "elasticdl_tpu/common/jax_compat.py",
        textwrap.dedent("""
            import jax

            def jit_compiled(fun, name=None, expected_variants=1):
                return jax.jit(fun)
        """),
    )
    assert run_passes([src], [JitShimPass()]) == []


# ---- jit-stability (v6) ----

JIT_STABILITY_SEEDED = """
    from elasticdl_tpu.common.jax_compat import jit_compiled

    class Stepper:
        def step(self, x):
            out = jit_compiled(self._fn, name="s.direct")(x)
            return out

        def step2(self, x):
            f = jit_compiled(self._fn, name="s.local")
            return f(x)
"""

JIT_STABILITY_CLEAN = """
    import jax
    from elasticdl_tpu.common.jax_compat import jit_compiled

    _module_step = jit_compiled(lambda x: x, name="s.mod")
    _module_step(1)

    class Stepper:
        def step(self, x):
            if self._fn is None:
                self._fn = jit_compiled(self._impl, name="s.memo")
            return self._fn(x)

        def build(self):
            return jit_compiled(self._impl, name="s.builder")

        def bucketed(self, shapes):
            for n in shapes:
                self._cache[n] = jit_compiled(self._impl, name="s.bucket")
"""


def test_jit_stability_seeded_and_clean():
    from elasticdl_tpu.analysis.jit_discipline import JitStabilityPass

    findings = _lint(JIT_STABILITY_SEEDED, [JitStabilityPass()])
    assert _rules(findings) == {"jit-stability"}
    assert len(findings) == 2  # direct-invoke + local-bound-and-called
    assert any("created and invoked in one expression" in f.message
               for f in findings)
    assert any("bound to local 'f'" in f.message for f in findings)
    # Module-level bind, self-attr memo, builder return, cache subscript:
    # every legal ownership shape is silent.
    assert _lint(JIT_STABILITY_CLEAN, [JitStabilityPass()]) == []


def test_jit_stability_waivable_with_reason():
    from elasticdl_tpu.analysis.jit_discipline import JitStabilityPass

    src = """
        from elasticdl_tpu.common.jax_compat import jit_compiled

        def probe(fn, x):
            # graftlint: allow[jit-stability] one-shot probe: runs once per process
            f = jit_compiled(fn, name="p.probe")
            return f(x)
    """
    assert _lint(src, [JitStabilityPass()]) == []


# ---- transfer-discipline (v6) ----

TRANSFER_SEEDED = """
    import numpy as np

    class Trainer:
        # jit-boundary: returns device buffers off the compiled step
        def step(self, state, batch):
            return state

    class Worker:
        def __init__(self):
            self.trainer = Trainer()

        # hot-path
        def loop(self, state, batch):
            out = self.trainer.step(state, batch)
            return float(out)
"""

TRANSFER_CLEAN = """
    import numpy as np

    class Trainer:
        # jit-boundary
        def step(self, state, batch):
            return state

    class Worker:
        def __init__(self):
            self.trainer = Trainer()

        # hot-path
        def loop(self, state, batch):
            out = self.trainer.step(state, batch)
            with self.phases.phase("step_wait"):
                host = float(out)  # accounted: the deliberate drain
            return host

        def offline_report(self, state, batch):
            out = self.trainer.step(state, batch)
            return float(out)  # not hot-path: scoping is the point
"""


def test_transfer_discipline_seeded_and_clean():
    from elasticdl_tpu.analysis.jit_discipline import TransferDisciplinePass

    findings = _lint(TRANSFER_SEEDED, [TransferDisciplinePass()])
    assert _rules(findings) == {"transfer-discipline"}
    assert len(findings) == 1
    assert "float() over a jit-boundary value" in findings[0].message
    assert _lint(TRANSFER_CLEAN, [TransferDisciplinePass()]) == []


def test_transfer_discipline_propagates_through_helpers():
    # The wrapped transfer the per-function view cannot see: a hot-path
    # function reaching np.asarray-of-step-output through a helper — the
    # blocking-propagation shape, with the witness chain in the message.
    from elasticdl_tpu.analysis.jit_discipline import TransferDisciplinePass

    src = """
        import numpy as np

        class Worker:
            # jit-boundary
            def step(self, state):
                return state

            def _settle(self, state):
                out = self.step(state)
                return np.asarray(out)

            # hot-path
            def loop(self, state):
                return self._settle(state)
    """
    findings = _lint(src, [TransferDisciplinePass()])
    assert len(findings) == 1
    f = findings[0]
    assert "callee chain materializes" in f.message
    assert "np.asarray" in f.message  # witness down to the primitive


def test_transfer_discipline_infers_boundary_through_returns():
    # run_step returns self.step(...): boundary-ness propagates through
    # the return fixpoint, so only the innermost function needs the
    # annotation (the Trainer.run_* adoption shape).
    from elasticdl_tpu.analysis.jit_discipline import TransferDisciplinePass

    src = """
        class Worker:
            # jit-boundary
            def step(self, state):
                return state

            def run_step(self, state):
                return self.step(state)

            # hot-path
            def loop(self, state):
                out = self.run_step(state)
                return out.item()
    """
    findings = _lint(src, [TransferDisciplinePass()])
    assert len(findings) == 1
    assert ".item() materializes" in findings[0].message


def test_transfer_discipline_jit_bound_local_flow():
    # out = step(x) where step came from jit_compiled: jit-flow without
    # any annotation — the syntactic half of the boundary model.
    from elasticdl_tpu.analysis.jit_discipline import TransferDisciplinePass

    src = """
        from elasticdl_tpu.common.jax_compat import jit_compiled

        # hot-path
        def loop(fn, x):
            step = jit_compiled(fn, name="m.step")
            out = step(x)
            return out.tolist()
    """
    findings = _lint(src, [TransferDisciplinePass()])
    assert len(findings) == 1
    assert ".tolist() materializes" in findings[0].message


def test_transfer_discipline_waived_primitive_does_not_propagate():
    from elasticdl_tpu.analysis.jit_discipline import TransferDisciplinePass

    src = """
        import numpy as np

        class Worker:
            # jit-boundary
            def step(self, state):
                return state

            def _settle(self, state):
                out = self.step(state)
                # graftlint: allow[transfer-discipline] the settle IS the product
                return np.asarray(out)

            # hot-path
            def loop(self, state):
                return self._settle(state)
    """
    assert _lint(src, [TransferDisciplinePass()]) == []


def test_transfer_discipline_except_handler_exempt():
    from elasticdl_tpu.analysis.jit_discipline import TransferDisciplinePass

    src = """
        class Worker:
            # jit-boundary
            def step(self, state):
                return state

            # hot-path
            def loop(self, state):
                out = self.step(state)
                try:
                    return out
                except Exception:
                    return float(out)  # error path: off the hot path
    """
    assert _lint(src, [TransferDisciplinePass()]) == []


# ---- thread-map: functools.partial targets (v6 satellite) ----

def test_thread_map_resolves_partial_targets():
    from elasticdl_tpu.analysis.thread_map import shared_thread_map

    src = SourceFile("mod.py", textwrap.dedent("""
        import functools
        import threading
        from functools import partial

        class W:
            def start(self, pool):
                t = threading.Thread(
                    target=functools.partial(self._beat, 1), daemon=True
                )
                t.start()
                pool.submit(partial(self._load, "k"))

            def _beat(self, n):
                pass

            def _load(self, key):
                pass
    """))
    tmap = shared_thread_map([src])
    roles = tmap.dump()["roles"]
    assert "mod:W._beat" in roles.get("thread:_beat", [])
    assert "mod:W._load" in roles.get("pool:_load", [])


def test_shared_state_sees_through_partial_spawn():
    # The muted-check regression the satellite fixes: a racy write inside
    # a partial-wrapped thread target must now be a shared-state finding.
    from elasticdl_tpu.analysis.shared_state import SharedStatePass

    src = """
        import functools
        import threading

        class W:
            def __init__(self):
                self._hits = 0

            def start(self):
                threading.Thread(
                    target=functools.partial(self._bump, 1), daemon=True
                ).start()

            def _bump(self, n):
                self._hits += n

            def report(self):
                print(self._hits)

        def main():
            w = W()
            w.start()
            w.report()
    """
    findings = _lint(src, [SharedStatePass()])
    assert len(findings) == 1
    assert "_hits" in findings[0].message


# ---- declared_sites (the artifact's static budget table) ----

def test_declared_sites_harvest():
    from elasticdl_tpu.analysis.jit_discipline import declared_sites

    src = SourceFile("mod.py", textwrap.dedent("""
        from elasticdl_tpu.common.jax_compat import jit_compiled, jit_donating

        def a(fn):
            return jit_compiled(fn, name="m.step", expected_variants=2)

        def b(fn):
            return jit_donating(fn, name="m.step", expected_variants=1)

        def c(fn, n):
            return jit_compiled(fn, name="m.buckets", expected_variants=n)

        def d(fn, variant_budget=3):
            return jit_compiled(
                fn, name="m.param", expected_variants=variant_budget)
    """))
    sites = declared_sites([src])
    assert sites["m.step"]["budget"] == 2  # max across sites
    assert len(sites["m.step"]["sites"]) == 2
    assert not sites["m.step"]["dynamic"]
    assert sites["m.buckets"]["budget"] is None  # unresolvable expression
    # expected_variants=<param>: resolved through the parameter default
    # (the trainer-builder shape), marked dynamic since callers may
    # override upward.
    assert sites["m.param"]["budget"] == 3 and sites["m.param"]["dynamic"]


# ---- durable-write-discipline (v7) ----

DURABLE_SEEDED = """
    import json
    import os

    JOURNAL_FILENAME = "master_journal.wal"  # durable-file

    def persist(directory, rec):
        path = os.path.join(directory, JOURNAL_FILENAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)
"""

DURABLE_CLEAN = """
    import os

    from elasticdl_tpu.common import durable

    JOURNAL_FILENAME = "master_journal.wal"  # durable-file

    def persist(directory, rec):
        path = os.path.join(directory, JOURNAL_FILENAME)
        durable.atomic_publish_json(path, rec)
"""


def test_durable_write_seeded_vs_clean():
    findings = _lint(DURABLE_SEEDED, [DurableWriteDisciplinePass()])
    # three independent violations in one hand-rolled publish: the
    # hand-rolled temp name, the raw write-mode open of the tainted local,
    # and the raw os.replace.
    assert _rules(findings) == {"durable-write-discipline"}
    assert len(findings) == 3
    assert _lint(DURABLE_CLEAN, [DurableWriteDisciplinePass()]) == []


DURABLE_ATTR_SEEDED = """
    import os

    REGISTRY_FILENAME = "pod_registry.json"  # durable-file

    class Registry:
        def __init__(self, directory):
            self._path = os.path.join(directory, REGISTRY_FILENAME)

        def save(self, blob):
            fd = os.open(self._path, os.O_WRONLY | os.O_CREAT)
            os.write(fd, blob)
            os.close(fd)
"""


def test_durable_write_taint_flows_through_self_attr():
    # The path reaches the write as self._path, assigned from the
    # constant in __init__: the class-wide attr taint must carry it to
    # the write-flavored os.open in save().
    findings = _lint(DURABLE_ATTR_SEEDED, [DurableWriteDisciplinePass()])
    assert _rules(findings) == {"durable-write-discipline"}
    assert any("os.open" in f.message for f in findings)


def test_hand_rolled_rename_flagged_without_constants():
    # os.replace/os.rename are unconditional: every rename IS a publish
    # commit and belongs in durable.py, tainted operands or not.
    findings = _lint(
        """
        import os

        def swap(a, b):
            os.rename(a, b)
        """,
        [DurableWriteDisciplinePass()],
    )
    assert _rules(findings) == {"durable-write-discipline"}


def test_durable_module_itself_exempt():
    src = """
        import os

        def commit(tmp, path):
            os.replace(tmp, path)
    """
    assert (
        lint_text(
            textwrap.dedent(src),
            [DurableWriteDisciplinePass()],
            path="elasticdl_tpu/common/durable.py",
        )
        == []
    )
    # the same text anywhere else is a violation
    assert _lint(src, [DurableWriteDisciplinePass()]) != []


def test_durable_write_waiver_and_stale():
    waived = """
        import os

        JOURNAL_FILENAME = "j.wal"  # durable-file

        def persist(directory, data):
            path = os.path.join(directory, JOURNAL_FILENAME)
            # graftlint: allow[durable-write-discipline] migration staged for next PR
            with open(path, "w") as f:
                f.write(data)
    """
    assert _lint(waived, [DurableWriteDisciplinePass()]) == []
    stale = """
        from elasticdl_tpu.common import durable

        JOURNAL_FILENAME = "j.wal"  # durable-file

        def persist(path, data):
            # graftlint: allow[durable-write-discipline] nothing here needs this
            durable.atomic_publish(path, data)
    """
    assert _rules(_lint(stale, [DurableWriteDisciplinePass()])) == {
        "stale-waiver"
    }


# ---- recovery-read-discipline (v7) ----

RECOVERY_SEEDED = """
    import json

    # recovery-path
    def load(path):
        with open(path) as f:
            return json.load(f)
"""

RECOVERY_CLEAN = """
    from elasticdl_tpu.common import durable

    # recovery-path
    def load(path):
        records, torn = durable.read_wal(path)
        return records
"""


def test_recovery_read_seeded_vs_clean():
    findings = _lint(RECOVERY_SEEDED, [RecoveryReadDisciplinePass()])
    assert _rules(findings) == {"recovery-read-discipline"}
    assert _lint(RECOVERY_CLEAN, [RecoveryReadDisciplinePass()]) == []


def test_raw_read_of_durable_path_outside_recovery_fn():
    # Reading a durable file from an UNANNOTATED function is the other
    # half: crash states (torn tail, non-compliant tear) reach every
    # reader, so every reader must route through the tolerant API.
    findings = _lint(
        """
        import os

        REGISTRY_FILENAME = "pod_registry.json"  # durable-file

        def peek(directory):
            path = os.path.join(directory, REGISTRY_FILENAME)
            with open(path) as f:
                return f.read()
        """,
        [RecoveryReadDisciplinePass()],
    )
    assert _rules(findings) == {"recovery-read-discipline"}


def test_v7_passes_registered():
    kinds = {type(p) for p in all_passes()}
    assert DurableWriteDisciplinePass in kinds
    assert RecoveryReadDisciplinePass in kinds


def test_cli_durables_dump():
    out = subprocess.run(
        [
            sys.executable, "tools/graftlint.py", "elasticdl_tpu", "tools",
            "--durables",
        ],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert {
        "JOURNAL_FILENAME", "MANIFEST_NAME", "METRICS_FILENAME",
        "PROGRESS_FILENAME", "REGISTRY_FILENAME",
    } <= set(doc)
    j = doc["JOURNAL_FILENAME"]
    assert j["file"] == "master_journal.wal"
    assert any(w.endswith(" rotate") for w in j["writers"])
    assert any("read_journal" in r for r in j["recovery_readers"])


# ---- wire-discipline (v8) ----

# The schema header every v8 fixture shares: the pass EVALUATES these
# literals (never imports them), so the fixture only has to parse.
WIRE_HEADER = """
    from elasticdl_tpu.common.rpc import JsonRpcClient, MessageSchema

    _STR = (str,)
    _INT = (int,)
    _DICT = (dict,)

    PROTOCOL_VERSION = 1

    MASTER_SCHEMAS = {
        "Ping": MessageSchema(
            required={"worker_id": _STR}, optional={"lease": _INT},
            since={"lease": 9},
        ),
    }
    for _method_schema in MASTER_SCHEMAS.values():
        _method_schema.optional.setdefault("trace", _DICT)
        _method_schema.since.setdefault("trace", 12)

    MASTER_RESPONSE_SCHEMAS = {
        "Ping": MessageSchema(
            required={"version": _INT}, optional={"eta": _INT},
            since={"eta": 9},
        ),
    }
"""

WIRE_SENDER_SEEDED = WIRE_HEADER + """
    def poll(client, wid):
        return client.call("Ping", {"worker_id": wid, "leese": 1})
"""

WIRE_SENDER_CLEAN = WIRE_HEADER + """
    def poll(client, wid):
        payload = {"worker_id": wid}
        payload["lease"] = 4
        payload.setdefault("trace", {})
        return client.call("Ping", payload)
"""


def test_wire_sender_undeclared_key_seeded_vs_clean():
    findings = _lint(WIRE_SENDER_SEEDED, [WireDisciplinePass()])
    assert _rules(findings) == {"wire-discipline"}
    assert len(findings) == 1
    assert "'leese'" in findings[0].message
    # The clean twin also proves the tracked-local grammar (literal
    # assign + const-subscript grow + setdefault) and the envelope-loop
    # evaluation ("trace" only exists via the setdefault loop).
    assert _lint(WIRE_SENDER_CLEAN, [WireDisciplinePass()]) == []


WIRE_RECEIVER_SEEDED = WIRE_HEADER + """
    class Servicer:
        def __init__(self):
            self._handlers = {"Ping": self._ping}

        def _ping(self, req):
            return {"version": req["lease"]}
"""

WIRE_RECEIVER_CLEAN = WIRE_HEADER + """
    class Servicer:
        def __init__(self):
            self._handlers = {"Ping": self._ping}

        def _ping(self, req):
            wid = req["worker_id"]
            return {"version": int(req.get("lease", 1)), "w": wid}
"""


def test_wire_receiver_optional_subscript_seeded_vs_clean():
    findings = _lint(WIRE_RECEIVER_SEEDED, [WireDisciplinePass()])
    assert _rules(findings) == {"wire-discipline"}
    assert len(findings) == 1
    assert "OPTIONAL" in findings[0].message
    assert ".get()" in findings[0].message
    # Clean twin: REQUIRED subscript is legal, optional via .get().
    # NOTE the response dict's "w" key is NOT judged — only reads are.
    assert _lint(WIRE_RECEIVER_CLEAN, [WireDisciplinePass()]) == []


WIRE_RECEIVER_HELPER_SEEDED = WIRE_HEADER + """
    class Servicer:
        def __init__(self):
            self._handlers = {"Ping": self._ping}

        def _ping(self, req):
            self._bank(req)
            return {"version": 1}

        def _bank(self, msg):
            return msg["trace"]
"""


def test_wire_receiver_helper_propagation():
    # The message param's methods flow through the same-file helper call:
    # the optional-subscript finding lands in _bank, not _ping.
    findings = _lint(WIRE_RECEIVER_HELPER_SEEDED, [WireDisciplinePass()])
    assert _rules(findings) == {"wire-discipline"}
    assert "'trace'" in findings[0].message


WIRE_RESPONSE_SEEDED = WIRE_HEADER + """
    def poll(client, wid):
        resp = client.call("Ping", {"worker_id": wid})
        return resp["eta"]
"""

WIRE_RESPONSE_CLEAN = WIRE_HEADER + """
    def poll(client, wid):
        resp = client.call("Ping", {"worker_id": wid})
        return resp["version"], resp.get("eta")
"""


def test_wire_client_response_subscript_seeded_vs_clean():
    findings = _lint(WIRE_RESPONSE_SEEDED, [WireDisciplinePass()])
    assert _rules(findings) == {"wire-discipline"}
    assert "response" in findings[0].message
    assert _lint(WIRE_RESPONSE_CLEAN, [WireDisciplinePass()]) == []


def test_wire_discipline_waiver_and_stale():
    waived = WIRE_HEADER + """
    def poll(client, wid):
        # graftlint: allow[wire-discipline] probing the master's unknown-field counter
        return client.call("Ping", {"worker_id": wid, "probe": 1})
    """
    assert _lint(waived, [WireDisciplinePass()]) == []
    stale = WIRE_HEADER + """
    def poll(client, wid):
        # graftlint: allow[wire-discipline] nothing here needs this
        return client.call("Ping", {"worker_id": wid})
    """
    assert _rules(_lint(stale, [WireDisciplinePass()])) == {"stale-waiver"}


# ---- wire-evolution (v8) ----


def _wire_sources(src: str):
    return [SourceFile("fixture.py", textwrap.dedent(src))]


def test_wire_evolution_clean_against_matching_lock():
    lock = wire_fingerprint(_wire_sources(WIRE_HEADER))
    assert lock["protocol_version"] == 1
    assert "request:Ping" in lock["methods"]
    # since from both the literal and the envelope loop evaluated:
    assert lock["methods"]["request:Ping"]["since"] == {
        "lease": 9, "trace": 12,
    }
    assert _lint(WIRE_HEADER, [WireEvolutionPass(lock_data=lock)]) == []


def test_wire_evolution_breaking_drift_without_bump():
    lock = wire_fingerprint(_wire_sources(WIRE_HEADER))
    # The lock remembers a field the code no longer declares (= the diff
    # REMOVED it) ...
    lock["methods"]["request:Ping"]["optional"]["gone"] = ["str"]
    findings = _lint(WIRE_HEADER, [WireEvolutionPass(lock_data=lock)])
    assert _rules(findings) == {"wire-evolution"}
    assert any("removed field 'gone'" in f.message for f in findings)
    assert any("bump PROTOCOL_VERSION" in f.message for f in findings)
    # ... and a type change / new REQUIRED field are the other two
    # breaking classes.
    lock2 = wire_fingerprint(_wire_sources(WIRE_HEADER))
    lock2["methods"]["request:Ping"]["required"]["worker_id"] = ["int"]
    del lock2["methods"]["response:Ping"]["required"]["version"]
    findings2 = _lint(WIRE_HEADER, [WireEvolutionPass(lock_data=lock2)])
    msgs = " | ".join(f.message for f in findings2)
    assert "changed accepted types" in msgs
    assert "added REQUIRED field 'version'" in msgs


def test_wire_evolution_drift_with_version_bump():
    bumped = WIRE_HEADER.replace(
        "PROTOCOL_VERSION = 1", "PROTOCOL_VERSION = 2"
    )
    stale_lock = wire_fingerprint(_wire_sources(WIRE_HEADER))
    # Bumped but the lock still records v1: ONE finding — regenerate —
    # regardless of how breaking the drift is.
    findings = _lint(bumped, [WireEvolutionPass(lock_data=stale_lock)])
    assert len(findings) == 1
    assert "regenerate" in findings[0].message
    # Bump + regenerated lock in the same diff: clean by construction.
    fresh_lock = wire_fingerprint(_wire_sources(bumped))
    assert _lint(bumped, [WireEvolutionPass(lock_data=fresh_lock)]) == []


def test_wire_evolution_additive_drift_asks_regenerate_only():
    grown = WIRE_HEADER.replace(
        'optional={"lease": _INT}', 'optional={"lease": _INT, "tags": _DICT}'
    )
    lock = wire_fingerprint(_wire_sources(WIRE_HEADER))
    findings = _lint(grown, [WireEvolutionPass(lock_data=lock)])
    assert len(findings) == 1
    assert "additive" in findings[0].message
    assert "bump" not in findings[0].message


def test_wire_evolution_silent_on_schema_free_fixtures():
    # Fixture files with no *_SCHEMAS tables must not drag the repo lock
    # into every other test's lint run.
    assert _lint(LOCK_SEEDED, [WireEvolutionPass(lock_data={})]) == []


def test_wire_lock_matches_committed_schemas():
    # The committed lock IS the current fingerprint — wire-evolution
    # judges the real repo against it in test_repo_lints_clean, so a
    # schema edit without --update-wire-lock fails tier-1 twice over.
    from elasticdl_tpu.analysis.core import load_sources

    sources, errs = load_sources(
        [os.path.join(REPO, "elasticdl_tpu", "common", "rpc.py")],
        rel_to=REPO,
    )
    assert errs == []
    with open(os.path.join(REPO, "artifacts", "wire_schema.lock.json")) as f:
        lock = json.load(f)
    assert lock == wire_fingerprint(sources)


def test_v8_passes_registered():
    kinds = {type(p) for p in all_passes()}
    assert WireDisciplinePass in kinds
    assert WireEvolutionPass in kinds


def test_cli_wire_dump():
    out = subprocess.run(
        [
            sys.executable, "tools/graftlint.py", "elasticdl_tpu", "tools",
            "--wire",
        ],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["protocol_version"] == 1
    methods = doc["methods"]
    assert {"GetTask", "ReportTaskResult", "Heartbeat", "Predict"} <= set(
        methods
    )
    gt = methods["GetTask"]
    assert gt["request"]["required"] == {"worker_id": ["str"]}
    assert gt["response"]["required"] == {"finished": ["bool"]}
    # Both resolution paths: the master's method_table form and the
    # serving tier's dict-literal wiring.
    assert any("servicer.py" in r for r in gt["receivers"])
    assert any(
        "serving/server.py" in r for r in methods["Predict"]["receivers"]
    )
    assert gt["senders"], "worker GetTask call site must resolve"
