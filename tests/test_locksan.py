"""common/locksan.py: the runtime lock-order sanitizer must catch a seeded
two-thread lock inversion DETERMINISTICALLY (order checking is edge-based,
not timing-based: the second acquisition order trips the assertion even
though the threads never actually collide) and stay silent on the clean
twin.  Tier-1 runs with GRAFT_LOCKSAN=1 (tests/conftest.py), so these
wrappers are live in every threaded suite."""

import os
import threading

import pytest

from elasticdl_tpu.common import locksan


@pytest.fixture(autouse=True)
def _fresh_edges():
    # The observed-order registry is process-global by design (the order
    # contract spans threads); tests isolate by clearing it.
    locksan.reset()
    yield
    locksan.reset()


def test_suite_runs_sanitized():
    # The conftest contract this file documents: tier-1 suites run with
    # the sanitizer ON, so worker/servicer/PS/pod-manager locks assert
    # their declared order at runtime.
    assert os.environ.get("GRAFT_LOCKSAN") == "1"
    assert locksan.enabled()
    assert isinstance(locksan.lock("T.probe"), locksan._SanLock)


def _run_in_thread(fn):
    """Run ``fn`` on a thread; return the exception it raised (or None).
    join() sequences the threads completely — no reliance on timing."""
    box = [None]

    def wrapper():
        try:
            fn()
        except Exception as e:  # noqa: BLE001 - the test inspects it
            box[0] = e

    t = threading.Thread(target=wrapper, daemon=True)
    t.start()
    t.join(10.0)
    assert not t.is_alive(), "sanitizer test thread wedged"
    return box[0]


def test_two_thread_inversion_caught_deterministically():
    a = locksan.lock("Inv.a")
    b = locksan.lock("Inv.b")

    def first():  # establishes the order a -> b
        with a:
            with b:
                pass

    def second():  # inverts it: b -> a
        with b:
            with a:
                pass

    assert _run_in_thread(first) is None
    err = _run_in_thread(second)
    assert isinstance(err, locksan.LockOrderViolation)
    assert "Inv.a" in str(err) and "Inv.b" in str(err)
    assert "inversion" in str(err)


def test_two_thread_consistent_order_clean_twin():
    a = locksan.lock("Clean.a")
    b = locksan.lock("Clean.b")

    def first():
        with a:
            with b:
                pass

    def second():  # same order: fine
        with a:
            with b:
                pass

    assert _run_in_thread(first) is None
    assert _run_in_thread(second) is None
    assert (("Clean.a", "Clean.b")) in locksan.observed_edges()


def test_leaf_declaration_enforced():
    leaf = locksan.lock("Leaf.l", leaf=True)
    other = locksan.lock("Leaf.o")
    with pytest.raises(locksan.LockOrderViolation, match="leaf"):
        with leaf:
            with other:
                pass
    # The converse direction is legal: a leaf may be acquired last.
    with other:
        with leaf:
            pass


def test_before_declaration_enforced():
    first = locksan.lock("Ord._first", before=("_second",))
    second = locksan.lock("Ord._second")
    with first:
        with second:
            pass  # declared order: fine
    with pytest.raises(locksan.LockOrderViolation, match="before"):
        with second:
            with first:
                pass


def test_nonreentrant_self_reacquire_raises_instead_of_deadlocking():
    lk = locksan.lock("Self.l")
    with pytest.raises(locksan.LockOrderViolation, match="re-acquired"):
        with lk:
            with lk:
                pass


def test_rlock_reentry_is_legal():
    lk = locksan.rlock("Re.l")
    with lk:
        with lk:
            assert lk.locked()


def test_peer_instances_of_same_name_are_not_ordered():
    # Two workers in one process: each has a "Worker._ckpt_lock".  Peer
    # instances have no defined mutual order — nesting them must not trip
    # the self-deadlock or inversion checks.
    a = locksan.lock("Peer._ckpt_lock")
    b = locksan.lock("Peer._ckpt_lock")
    with a:
        with b:
            pass
    with b:
        with a:
            pass


def test_release_order_need_not_be_lifo():
    a = locksan.lock("Lifo.a")
    b = locksan.lock("Lifo.b")
    a.acquire()
    b.acquire()
    a.release()  # non-LIFO release of distinct locks is legal
    b.release()
    with a:
        with b:
            pass  # held bookkeeping survived the non-LIFO release


def test_disabled_returns_plain_lock(monkeypatch):
    monkeypatch.setenv("GRAFT_LOCKSAN", "0")
    lk = locksan.lock("Off.l", leaf=True)
    assert isinstance(lk, type(threading.Lock()))
    rlk = locksan.rlock("Off.r")
    assert isinstance(rlk, type(threading.RLock()))


def test_violation_reports_first_witness_site():
    a = locksan.lock("Wit.a")
    b = locksan.lock("Wit.b")
    with a:
        with b:
            pass
    try:
        with b:
            with a:
                pass
    except locksan.LockOrderViolation as e:
        # The message names where the OPPOSITE order was first observed.
        assert "test_locksan.py" in str(e)
    else:
        pytest.fail("inversion not raised")


# ---- contention stats (r16) ----

def test_contention_stats_off_by_default_and_opt_in():
    lk = locksan.lock("Stat.cold")
    with lk:
        pass
    # reset() in the fixture cleared stats AND the enable flag persists
    # process-wide once a collector installs it; judge only the per-name
    # aggregates here.
    locksan.enable_contention_stats((1.0, 10.0))
    with lk:
        pass
    snap = locksan.contention_snapshot()
    assert "Stat.cold" in snap
    rec = snap["Stat.cold"]
    assert rec["acquires"] == 1
    wm = rec["wait_ms"]
    assert wm["edges"] == [1.0, 10.0]
    assert len(wm["counts"]) == 3 and sum(wm["counts"]) == 1
    assert wm["count"] == 1
    # An uncontended acquire waits ~0 ms: the under-first-edge bin.
    assert wm["counts"][0] == 1


def test_contention_stats_measure_blocked_wait():
    locksan.enable_contention_stats((1.0, 10.0, 100.0))
    lk = locksan.lock("Stat.busy")
    lk.acquire()
    release_timer = threading.Timer(0.05, lk.release)
    release_timer.daemon = True

    def contender():
        release_timer.start()
        with lk:  # blocks ~50 ms until the timer releases
            pass

    t = threading.Thread(target=contender, daemon=True)
    t.start()
    t.join(10.0)
    assert not t.is_alive()
    rec = locksan.contention_snapshot()["Stat.busy"]
    assert rec["acquires"] == 2
    assert rec["wait_ms"]["sum"] >= 40.0  # the blocked acquire's wait


def test_reset_clears_contention_stats():
    locksan.enable_contention_stats((1.0,))
    with locksan.lock("Stat.reset"):
        pass
    assert "Stat.reset" in locksan.contention_snapshot()
    locksan.reset()
    assert locksan.contention_snapshot() == {}
