"""Multi-host backend plumbing: membership-derived DistributedSpec, address
registration through the rendezvous, single-host no-op behavior
(SURVEY.md §5 distributed comm backend).  Real multi-process
jax.distributed needs multiple hosts; these tests pin the control-plane
contract that feeds it."""

from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.parallel.distributed import (
    DistributedSpec,
    initialize,
    spec_from_membership,
)


def test_spec_from_membership_multihost():
    membership = {
        "version": 3,
        "ranks": {"w-a": 0, "w-b": 1, "w-c": 2},
        "world_size": 3,
        "addresses": {"w-a": "10.0.0.1", "w-b": "10.0.0.2", "w-c": "10.0.0.3"},
    }
    spec = spec_from_membership(membership, "w-b", coordinator_port=9000)
    assert spec.enabled
    assert spec.coordinator_address == "10.0.0.1:9000"
    assert spec.num_processes == 3
    assert spec.process_id == 1


def test_spec_single_host_disabled():
    membership = {"ranks": {"w-a": 0}, "addresses": {"w-a": "10.0.0.1"}}
    assert not spec_from_membership(membership, "w-a").enabled
    # no addresses advertised -> single-host mode regardless of world size
    membership = {"ranks": {"w-a": 0, "w-b": 1}, "addresses": {}}
    assert not spec_from_membership(membership, "w-a").enabled


def test_spec_missing_rank0_address_disabled():
    membership = {
        "ranks": {"w-a": 0, "w-b": 1},
        "addresses": {"w-b": "10.0.0.2"},
    }
    assert not spec_from_membership(membership, "w-b").enabled


def test_initialize_noop_for_single_process():
    # must not touch jax.distributed for a disabled spec
    initialize(DistributedSpec("", 1, 0))


def test_rendezvous_tracks_addresses():
    rdv = RendezvousServer()
    rdv.register("w-b", address="10.0.0.2")
    rdv.register("w-a", address="10.0.0.1")
    m = rdv.membership()
    assert m["addresses"] == {"w-a": "10.0.0.1", "w-b": "10.0.0.2"}
    assert m["ranks"] == {"w-a": 0, "w-b": 1}
    rdv.remove("w-a")
    m = rdv.membership()
    assert m["addresses"] == {"w-b": "10.0.0.2"}


def test_rendezvous_address_change_bumps_version():
    """A worker restarted on a new host must be re-discovered: same id,
    new address -> version bump so peers re-read membership."""
    rdv = RendezvousServer()
    v1 = rdv.register("w-a", address="10.0.0.1")
    assert rdv.register("w-a", address="10.0.0.1") == v1  # no spurious bump
    v2 = rdv.register("w-a", address="10.0.0.9")
    assert v2 > v1
    assert rdv.membership()["addresses"]["w-a"] == "10.0.0.9"


def test_pod_manager_restart_exit_is_budget_free():
    """Exit code 3 (multihost re-join restart) relaunches the slot without
    consuming the relaunch budget."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.master.pod_manager import (
        FakePodBackend,
        PodManager,
        PodPhase,
    )

    backend = FakePodBackend()
    config = JobConfig(max_worker_relaunch=1)
    mgr = PodManager(backend, config)
    mgr.start(1)
    for _ in range(4):  # far beyond the budget of 1
        [name] = mgr.live_pods()
        backend.set_phase(name, PodPhase.RESTART)
    [survivor] = mgr.live_pods()
    assert mgr.pod_info(survivor).relaunches == 0
    # a real failure still consumes budget afterwards
    backend.fail_pod(survivor)
    [relaunched] = mgr.live_pods()
    assert mgr.pod_info(relaunched).relaunches == 1


def test_rendezvous_reap_clears_addresses():
    t = [0.0]
    rdv = RendezvousServer(heartbeat_timeout_s=5.0, clock=lambda: t[0])
    rdv.register("w-a", address="10.0.0.1")
    t[0] = 10.0
    assert rdv.reap_dead() == ["w-a"]
    assert rdv.membership()["addresses"] == {}
