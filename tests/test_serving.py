"""Serving tier (r10): micro-batcher flush/padding/fan-back semantics,
hot-id embedding cache (incl. the stale-row generation guard), atomic
checkpoint publish + watcher, and the gRPC server end-to-end with a
zero-drop hot reload."""

import os
import threading
import time

import grpc
import numpy as np
import pytest

from elasticdl_tpu.common.checkpoint import (
    MANIFEST_NAME,
    publish_manifest,
    read_manifest,
)
from elasticdl_tpu.serving.checkpoint_watcher import CheckpointWatcher
from elasticdl_tpu.serving.embedding_cache import HotIdEmbeddingCache
from elasticdl_tpu.serving.micro_batcher import (
    MASK_KEY,
    BatcherClosed,
    BatcherOverloaded,
    MicroBatcher,
)

# ---------------------------------------------------------------- batcher


def _echo_runner(calls):
    """Runner that records the padded batch and echoes x * 2."""

    def run(batch, n_real):
        calls.append(({k: v.copy() for k, v in batch.items()}, n_real))
        return batch["x"] * 2.0, {"step": 3}

    return run


TMPL = {"x": np.zeros((1, 2), np.float32)}


def test_micro_batcher_deadline_flush_pads_and_masks():
    calls = []
    mb = MicroBatcher(_echo_runner(calls), TMPL, max_batch=8, max_delay_ms=25)
    try:
        t0 = time.monotonic()
        h = mb.submit({"x": np.full((2, 2), 3.0, np.float32)})
        out, meta = h.result(5.0)
        waited = time.monotonic() - t0
        # Flushed by the deadline, not by an (impossible) full batch, and
        # well before the fallback result timeout.
        assert waited < 2.0
        assert meta == {"step": 3}
        assert out.shape == (2, 2) and np.all(out == 6.0)
        batch, n_real = calls[0]
        assert n_real == 2
        # Padded to the fixed shape with zeros; mask marks the real rows.
        assert batch["x"].shape == (8, 2)
        assert np.all(batch["x"][2:] == 0.0)
        assert np.all(batch[MASK_KEY] == [1, 1, 0, 0, 0, 0, 0, 0])
        assert mb.stats()["flushes_deadline"] == 1
        assert mb.stats()["rows_padded"] == 6
    finally:
        mb.close()


def test_micro_batcher_full_flush_before_deadline():
    calls = []
    # Deadline far away: only a full batch can flush this fast.
    mb = MicroBatcher(_echo_runner(calls), TMPL, max_batch=4,
                      max_delay_ms=30_000)
    try:
        results = {}

        def client(i):
            h = mb.submit({"x": np.full((1, 2), float(i), np.float32)})
            results[i] = h.result(10.0)[0]

        threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # Each concurrent client got ITS OWN rows back (fan-back), doubled.
        for i in range(4):
            assert np.all(results[i] == 2.0 * i), (i, results[i])
        st = mb.stats()
        assert st["flushes_full"] == 1 and st["flushes_deadline"] == 0
        assert st["rows_served"] == 4 and st["rows_padded"] == 0
    finally:
        mb.close()


def test_micro_batcher_whole_request_never_splits():
    """A 3-row request into a max_batch=4 queue holding 2 rows must wait
    for the NEXT flush (whole-request fan-back), not straddle two."""
    calls = []
    mb = MicroBatcher(_echo_runner(calls), TMPL, max_batch=4, max_delay_ms=40)
    try:
        h1 = mb.submit({"x": np.full((2, 2), 1.0, np.float32)})
        h2 = mb.submit({"x": np.full((3, 2), 2.0, np.float32)})
        out1, _ = h1.result(5.0)
        out2, _ = h2.result(5.0)
        assert out1.shape == (2, 2) and np.all(out1 == 2.0)
        assert out2.shape == (3, 2) and np.all(out2 == 4.0)
        assert [n for _, n in calls] == [2, 3]
    finally:
        mb.close()


def test_micro_batcher_runner_error_fans_back_and_recovers():
    boom = {"armed": True}

    def runner(batch, n_real):
        if boom["armed"]:
            raise RuntimeError("model exploded")
        return batch["x"], {}

    mb = MicroBatcher(runner, TMPL, max_batch=2, max_delay_ms=10)
    try:
        h1 = mb.submit({"x": np.ones((1, 2), np.float32)})
        h2 = mb.submit({"x": np.ones((1, 2), np.float32)})
        for h in (h1, h2):
            with pytest.raises(RuntimeError, match="model exploded"):
                h.result(5.0)
        # The flusher survived the poisoned batch.
        boom["armed"] = False
        out, _ = mb.submit({"x": np.ones((1, 2), np.float32)}).result(5.0)
        assert out.shape == (1, 2)
    finally:
        mb.close()


def test_micro_batcher_rejects_malformed_in_the_callers_frame():
    """Validation happens at submit(), not during batch assembly — a bad
    request must fail alone, never fan an error to its flush-mates."""
    mb = MicroBatcher(lambda b, n: (b["x"], {}), TMPL, max_batch=2,
                      max_delay_ms=5)
    try:
        with pytest.raises(ValueError, match="1..2"):
            mb.submit({"x": np.ones((3, 2), np.float32)})  # oversize
        with pytest.raises(ValueError, match="missing feature"):
            mb.submit({"y": np.ones((1, 2), np.float32)})
        with pytest.raises(ValueError, match="trailing dims"):
            mb.submit({"x": np.ones((1, 5), np.float32)})
        # A good request co-queued around the rejects still serves.
        out, _ = mb.submit({"x": np.ones((1, 2), np.float32)}).result(5.0)
        assert out.shape == (1, 2)
    finally:
        mb.close()
    with pytest.raises(BatcherClosed):
        mb.submit({"x": np.ones((1, 2), np.float32)})


def test_micro_batcher_sheds_on_overload_and_expires_stale_requests():
    """Past the knee: submit() sheds at the queue bound (fast structured
    error), and requests older than drop_after_s fail at flush time
    instead of wasting a padded forward on a caller that already gave up."""
    gate = threading.Event()

    def slow_runner(batch, n_real):
        assert gate.wait(10.0)
        return batch["x"], {}

    mb = MicroBatcher(slow_runner, TMPL, max_batch=1, max_delay_ms=1,
                      max_queue_rows=2, drop_after_s=0.2)
    try:
        one = lambda: {"x": np.ones((1, 2), np.float32)}
        h_running = mb.submit(one())  # taken by the flusher, blocks in runner
        deadline = time.monotonic() + 5.0
        while mb.stats()["queued"] != 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        h_q1, h_q2 = mb.submit(one()), mb.submit(one())  # fill the bound
        with pytest.raises(BatcherOverloaded, match="shedding"):
            mb.submit(one())
        assert mb.stats()["shed_overload"] == 1
        time.sleep(0.3)  # queued requests age past drop_after_s
        gate.set()  # release the running flush; next take sheds expired
        out, _ = h_running.result(5.0)
        assert out.shape == (1, 2)
        for h in (h_q1, h_q2):
            with pytest.raises(TimeoutError, match="expired"):
                h.result(5.0)
        assert mb.stats()["expired"] == 2
        # Recovered: fresh requests serve normally.
        assert mb.submit(one()).result(5.0)[0].shape == (1, 2)
    finally:
        gate.set()
        mb.close()


# ------------------------------------- buckets + priority lanes (r19)


def test_micro_batcher_bucketed_padding_picks_smallest_bucket():
    """Each flush pads to the smallest declared bucket that holds its real
    rows — not to max_batch (the r10 design paid 94% padding there) and
    not to the exact size (which would retrace XLA per arbitrary n)."""
    calls = []
    mb = MicroBatcher(_echo_runner(calls), TMPL, max_batch=8, max_delay_ms=5,
                      batch_buckets=(1, 2, 4))
    try:
        assert mb.batch_buckets == (1, 2, 4, 8)  # max_batch always a bucket
        out1, _ = mb.submit({"x": np.ones((1, 2), np.float32)}).result(5.0)
        out3, _ = mb.submit({"x": np.ones((3, 2), np.float32)}).result(5.0)
        assert out1.shape == (1, 2) and out3.shape == (3, 2)
        # 1 real row -> bucket 1 (zero padding); 3 -> bucket 4 (1 pad row).
        assert [b["x"].shape[0] for b, _ in calls] == [1, 4]
        assert list(calls[1][0][MASK_KEY]) == [1, 1, 1, 0]
        st = mb.stats()
        assert st["flushes_by_bucket"] == {"1": 1, "2": 0, "4": 1, "8": 0}
        assert st["rows_padded"] == 1  # vs 12 padding both flushes to 8
    finally:
        mb.close()


def _gated_echo(calls, gate):
    """Echo runner whose FIRST flush parks until ``gate`` — lets a test
    queue both lanes behind a flush in flight, then observe exactly how
    the next flush admits them."""

    def run(batch, n_real):
        calls.append(({k: v.copy() for k, v in batch.items()}, n_real))
        if not gate.is_set():
            gate.wait(10.0)
        return batch["x"] * 2.0, {}

    return run


def test_micro_batcher_weighted_admission_packs_online_first():
    """Both lanes queued: online rows lead the flush even when bulk queued
    FIRST, and the head online request is exempt from the weighted cap (a
    wide online request must not starve behind a standing bulk queue)."""
    calls = []
    gate = threading.Event()
    mb = MicroBatcher(_gated_echo(calls, gate), TMPL, max_batch=4,
                      max_delay_ms=5, bulk_weight=0.25)
    try:
        mb.submit({"x": np.zeros((1, 2), np.float32)})  # occupies the flusher
        deadline = time.monotonic() + 5.0
        while mb.stats()["queued"] != 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        hb = mb.submit({"x": np.full((1, 2), 5.0, np.float32)}, lane="bulk")
        # 3 rows > cap_online (4 - 4*0.25 = 3 is the cap; head exemption
        # makes the full max_batch available to it).
        ho = mb.submit({"x": np.full((3, 2), 7.0, np.float32)})
        gate.set()
        assert np.all(hb.result(5.0)[0] == 10.0)
        assert np.all(ho.result(5.0)[0] == 14.0)
        batch, n_real = calls[1]
        assert n_real == 4
        # Online's 3 rows lead; bulk trickles in the remaining slot.
        assert np.all(batch["x"][:3] == 7.0) and np.all(batch["x"][3] == 5.0)
        st = mb.stats()
        assert st["lanes"]["online"]["rows_served"] == 4  # dummy + 3
        assert st["lanes"]["bulk"]["rows_served"] == 1
    finally:
        gate.set()
        mb.close()


def test_micro_batcher_bulk_trickle_guaranteed_under_online_pressure():
    """Online demand exceeding the batch: the weighted cap holds the excess
    online request to the NEXT flush so bulk still drains at its reserved
    trickle — weighted admission, not strict starvation-prone priority."""
    calls = []
    gate = threading.Event()
    mb = MicroBatcher(_gated_echo(calls, gate), TMPL, max_batch=4,
                      max_delay_ms=5, bulk_weight=0.25)
    try:
        mb.submit({"x": np.zeros((1, 2), np.float32)})  # occupies the flusher
        deadline = time.monotonic() + 5.0
        while mb.stats()["queued"] != 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        hb = [mb.submit({"x": np.full((1, 2), 5.0, np.float32)}, lane="bulk")
              for _ in range(2)]
        ho = [mb.submit({"x": np.full((2, 2), 7.0, np.float32)})
              for _ in range(2)]
        gate.set()
        for h in hb + ho:
            h.result(5.0)
        # Flush #2: online's first 2 rows (cap 3 blocks the second online
        # request) + both bulk rows.  Flush #3: the deferred online pair.
        batch2, n2 = calls[1]
        assert n2 == 4
        assert np.all(batch2["x"][:2] == 7.0) and np.all(batch2["x"][2:] == 5.0)
        batch3, n3 = calls[2]
        assert n3 == 2 and np.all(batch3["x"][:2] == 7.0)
    finally:
        gate.set()
        mb.close()


def test_micro_batcher_shed_bulk_first_with_exact_attribution():
    """Overload ordering: bulk sheds at its own lane bound, an online
    submit at the TOTAL bound evicts the newest queued bulk (which fails
    structured) before online would ever shed itself — and every shed is
    attributed to its lane in stats()."""
    gate = threading.Event()

    def parked(batch, n_real):
        assert gate.wait(10.0)
        return batch["x"], {}

    one = lambda: {"x": np.ones((1, 2), np.float32)}
    mb = MicroBatcher(parked, TMPL, max_batch=1, max_delay_ms=1,
                      max_queue_rows=4, bulk_queue_frac=0.5,
                      drop_after_s=30.0)
    try:
        h_running = mb.submit(one())  # taken by the flusher, parks
        deadline = time.monotonic() + 5.0
        while mb.stats()["queued"] != 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        # Bulk lane bound = 4 * 0.5 = 2 rows: third bulk sheds AT ITS OWN
        # bound while the queue still has capacity online can use.
        b1 = mb.submit(one(), lane="bulk")
        b2 = mb.submit(one(), lane="bulk")
        with pytest.raises(BatcherOverloaded, match="bulk lane"):
            mb.submit(one(), lane="bulk")
        assert mb.stats()["lanes"]["bulk"]["shed"] == 1
        # Online fills to the total bound...
        o1, o2 = mb.submit(one()), mb.submit(one())
        # ... and PAST it evicts the newest bulk first: b2 then b1.
        o3 = mb.submit(one())
        with pytest.raises(BatcherOverloaded, match="evicted"):
            b2.result(0.5)
        o4 = mb.submit(one())
        with pytest.raises(BatcherOverloaded, match="evicted"):
            b1.result(0.5)
        assert mb.stats()["lanes"]["bulk"]["shed"] == 3
        # No bulk left to evict: only now does online shed itself.
        with pytest.raises(BatcherOverloaded, match="shedding"):
            mb.submit(one())
        st = mb.stats()
        assert st["lanes"]["online"]["shed"] == 1
        assert st["shed_overload"] == 4  # lane-summed legacy total
        gate.set()
        for h in (h_running, o1, o2, o3, o4):
            assert h.result(5.0)[0].shape == (1, 2)
        st = mb.stats()
        assert st["lanes"]["online"]["rows_served"] == 5
        assert st["lanes"]["bulk"]["rows_served"] == 0
    finally:
        gate.set()
        mb.close()


def test_online_latency_survives_bulk_saturation():
    """The headline lane guarantee: a bulk flood saturating its lane (sheds
    observed) must neither shed nor starve online traffic — online p99
    stays bounded by a couple of flush walls, not by the bulk backlog."""

    def runner(batch, n_real):
        time.sleep(0.002)  # stands in for one forward
        return batch["x"], {}

    mb = MicroBatcher(runner, TMPL, max_batch=8, max_delay_ms=1,
                      max_queue_rows=32, bulk_weight=0.25)
    stop = threading.Event()

    def bulk_flood():
        while not stop.is_set():
            try:
                mb.submit({"x": np.ones((8, 2), np.float32)}, lane="bulk")
            except BatcherOverloaded:
                time.sleep(0.0005)  # lane full: the flood IS saturating

    flooder = threading.Thread(target=bulk_flood)
    flooder.start()
    lat = []
    try:
        time.sleep(0.05)  # let the bulk backlog establish
        for _ in range(60):
            t0 = time.monotonic()
            out, _ = mb.submit({"x": np.ones((1, 2), np.float32)}).result(5.0)
            lat.append(time.monotonic() - t0)
            assert out.shape == (1, 2)
            time.sleep(0.002)
    finally:
        stop.set()
        flooder.join(5.0)
    st = mb.stats()
    mb.close()
    assert st["lanes"]["bulk"]["shed"] > 0        # bulk lane saturated...
    assert st["lanes"]["bulk"]["rows_served"] > 0  # ...yet still drained
    assert st["lanes"]["online"]["shed"] == 0      # online never shed
    assert st["lanes"]["online"]["expired"] == 0
    lat.sort()
    # Bounds are generous for a loaded 1-core CI box; the point is "a few
    # flush walls", not "the 30 s result timeout" a starved lane would hit.
    assert lat[len(lat) // 2] < 0.25, lat
    assert lat[int(len(lat) * 0.99)] < 1.5, lat


# ------------------------------------------------------------------ cache


class _CountingStore:
    dim = 2

    def __init__(self):
        self.pulls = []
        self.gate = None  # optional Event: pull blocks until set

    def pull(self, ids):
        self.pulls.append(np.array(ids))
        if self.gate is not None:
            assert self.gate.wait(5.0)
        ids = np.asarray(ids, np.int64)
        return np.stack(
            [np.array([i, i + 0.5], np.float32) for i in ids]
        ) if ids.size else np.zeros((0, 2), np.float32)


def test_embedding_cache_hit_miss_lru_and_shapes():
    store = _CountingStore()
    cache = HotIdEmbeddingCache(store, capacity=2)
    out = cache.pull(np.array([[7, 9], [7, 7]]))  # any shape, like the store
    assert out.shape == (2, 2, 2)
    assert np.allclose(out[0, 0], [7, 7.5]) and np.allclose(out[0, 1], [9, 9.5])
    # One store pull, unique ids only.
    assert len(store.pulls) == 1 and sorted(store.pulls[0]) == [7, 9]
    cache.pull(np.array([7, 9]))  # all hits
    assert len(store.pulls) == 1
    cache.pull(np.array([11]))  # evicts the LRU id (7 was refreshed... 9? LRU order)
    st = cache.stats()
    assert st["size"] == 2 and st["evictions"] == 1
    cache.invalidate()
    assert len(cache) == 0
    assert cache.stats()["generation"] == 1


def test_embedding_cache_stale_rows_do_not_survive_invalidate():
    """The generation guard: a fetch in flight when invalidate() lands
    still serves ITS caller (the request predates the swap) but must not
    re-populate the cache with pre-swap rows."""
    store = _CountingStore()
    store.gate = threading.Event()
    cache = HotIdEmbeddingCache(store, capacity=64)
    out = {}

    def puller():
        out["rows"] = cache.pull(np.array([5]))

    t = threading.Thread(target=puller)
    t.start()
    # The fetch is parked inside store.pull; swap the weights now.
    deadline = time.monotonic() + 5.0
    while not store.pulls and time.monotonic() < deadline:
        time.sleep(0.01)
    cache.invalidate()
    store.gate.set()
    t.join(5.0)
    assert out["rows"].shape == (1, 2)  # caller still served
    assert len(cache) == 0  # stale row NOT cached
    st = cache.stats()
    assert st["stale_drops"] == 1
    # The next pull of the same id re-fetches post-swap rows.
    store.gate = None
    cache.pull(np.array([5]))
    assert len(store.pulls) == 2 and len(cache) == 1


# ---------------------------------------------------- manifest + watcher


def test_manifest_publish_atomic_roundtrip(tmp_path):
    d = str(tmp_path)
    assert read_manifest(d) is None
    publish_manifest(d, 12, code_rev="abc")
    m = read_manifest(d)
    assert m["step"] == 12 and m["code_rev"] == "abc"
    # No temp litter (the write committed via rename).
    assert [f for f in os.listdir(d) if f.startswith(MANIFEST_NAME)] == [
        MANIFEST_NAME
    ]
    # Garbage manifests read as "nothing published", never raise.
    with open(os.path.join(d, MANIFEST_NAME), "w") as f:
        f.write("{torn")
    assert read_manifest(d) is None
    with open(os.path.join(d, MANIFEST_NAME), "w") as f:
        f.write('{"step": "six"}')
    assert read_manifest(d) is None


def test_checkpoint_watcher_applies_changes_once(tmp_path):
    d = str(tmp_path)
    applied = []
    w = CheckpointWatcher(d, lambda step, m: applied.append(step),
                          poll_interval_s=60.0)
    assert w.poke() is False  # nothing published
    publish_manifest(d, 1)
    assert w.poke() is True and applied == [1]
    assert w.poke() is False and applied == [1]  # unchanged -> no re-apply
    publish_manifest(d, 2)
    assert w.poke() is True and applied == [1, 2]
    # A training restart can publish an OLDER step: serving follows.
    publish_manifest(d, 1)
    assert w.poke() is True and applied == [1, 2, 1]
    assert w.applied_step() == 1


def test_checkpoint_watcher_failed_reload_retries(tmp_path):
    d = str(tmp_path)
    calls = []

    def flaky(step, m):
        calls.append(step)
        if len(calls) == 1:
            raise IOError("volume hiccup")

    w = CheckpointWatcher(d, flaky, poll_interval_s=60.0)
    publish_manifest(d, 3)
    # A TRANSIENT failure (OSError) retries INSIDE the poke through the
    # shared backoff helper — a reload deferred a whole poll interval is a
    # whole poll interval of stale weights.
    assert w.poke() is True
    assert calls == [3, 3] and w.applied_step() == 3

    # A non-transient failure (corrupt checkpoint) is NOT hammered in-poke:
    # it defers to the next poll, which gets exactly one fresh attempt.
    hard = []

    def bad(step, m):
        hard.append(step)
        if len(hard) == 1:
            raise ValueError("corrupt checkpoint")

    w2 = CheckpointWatcher(d, bad, poll_interval_s=60.0)
    assert w2.poke() is False and hard == [3]
    assert w2.applied_step() is None
    assert w2.poke() is True
    assert hard == [3, 3] and w2.applied_step() == 3


def test_watcher_skips_step_already_loaded_at_startup(tmp_path):
    d = str(tmp_path)
    publish_manifest(d, 7)
    applied = []
    w = CheckpointWatcher(d, lambda step, m: applied.append(step),
                          poll_interval_s=60.0, initial_step=7)
    assert w.poke() is False and applied == []
    publish_manifest(d, 8)
    assert w.poke() is True and applied == [8]


# ----------------------------------------------------------- server e2e


def _wide_deep_tiny():
    from elasticdl_tpu.models.spec import load_model_spec

    return load_model_spec(
        "elasticdl_tpu.models", "wide_deep.model_spec",
        buckets=64, embedding_dim=4, hidden=(8,),
    )


def _census_features(n=1, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "dense": rng.rand(n, 5).astype(np.float32) * 50,
        "cat": rng.randint(0, 1 << 20, size=(n, 9)),
    }


def test_serving_server_end_to_end(tmp_path, devices):
    import jax

    from elasticdl_tpu.common.checkpoint import CheckpointManager
    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer
    from elasticdl_tpu.serving.client import ServingClient
    from elasticdl_tpu.serving.server import ServingServer

    spec = _wide_deep_tiny()
    ckpt_dir = str(tmp_path / "ckpt")
    server = ServingServer(
        spec, checkpoint_dir=ckpt_dir, max_batch=8, max_delay_ms=3,
        poll_interval_s=0.05,
    ).start()
    client = ServingClient(server.address)
    try:
        server.warmup()
        client.wait_ready(10.0)

        # Fresh weights serve (step -1) with the model's predict entry:
        # outputs are probabilities, single- and multi-example shapes work.
        r = client.predict(_census_features(1))
        assert r["model"] == "wide_deep" and r["step"] == -1
        assert len(r["outputs"]) == 1 and 0.0 <= r["outputs"][0] <= 1.0
        out3 = client.predict_outputs(_census_features(3))
        assert out3.shape == (3,)
        assert np.all((out3 >= 0) & (out3 <= 1))
        # A single example may omit the batch dim.
        flat = {k: v[0] for k, v in _census_features(1).items()}
        assert len(client.predict(flat)["outputs"]) == 1

        # Schema violations fail structured at the boundary.
        with pytest.raises(grpc.RpcError) as err:
            client.predict({"dense": [[1.0] * 5]})  # missing "cat"
        assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION
        assert "cat" in err.value.details()
        with pytest.raises(grpc.RpcError) as err:
            client.predict({"dense": [[1.0] * 4], "cat": [[0] * 9]})
        assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION

        info = client.model_info()
        assert info["model"] == "wide_deep"
        assert info["features"]["cat"]["example_shape"] == [9]
        assert info["batcher"]["rows_served"] >= 5

        # --- hot reload under concurrent traffic: zero dropped requests ---
        trainer = Trainer(
            spec,
            JobConfig(
                distribution_strategy=DistributionStrategy.PARAMETER_SERVER
            ),
            create_mesh([jax.devices()[0]]),
        )
        state = trainer.init_state(jax.random.key(0))
        params = jax.device_get(state.params)
        params["bias"] = np.array([9.0], np.float32)  # sigmoid(9) ~ 0.9999
        state = state.replace(params=params)
        mgr = CheckpointManager(ckpt_dir)
        mgr.save(5, jax.device_get(state), wait=True)
        mgr.publish(5)
        mgr.close()

        errors = []
        stop = threading.Event()

        def hammer(i):
            c = ServingClient(server.address)
            try:
                c.wait_ready(5.0)
                while not stop.is_set():
                    c.predict(_census_features(1, seed=i))
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                c.close()

        threads = [threading.Thread(target=hammer, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if client.model_info()["step"] == 5:
                break
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(10.0)
        assert not errors, errors  # the reload dropped no request
        info = client.model_info()
        assert info["step"] == 5 and info["reloads"] >= 1
        # The swap itself is a reference assignment: sub-millisecond even
        # on this loaded CPU box (bounded loosely for CI noise).
        assert info["last_swap_ms"] < 250.0
        # New weights actually serve.
        out = client.predict_outputs(_census_features(1))
        assert out[0] > 0.99
    finally:
        client.close()
        server.stop()


@pytest.mark.skipif(
    not __import__(
        "elasticdl_tpu.ps.host_store", fromlist=["native_lib_available"]
    ).native_lib_available(),
    reason="native host store unavailable",
)
def test_serving_host_tier_cache_invalidated_on_reload(tmp_path, devices):
    """Host-tier serving over a live PS shard: rows cache on first pull,
    the cache (not the PS) serves repeats, and a hot reload drops the
    cached rows so post-swap requests see the PS's CURRENT rows."""
    import jax

    from elasticdl_tpu.common.checkpoint import CheckpointManager
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer
    from elasticdl_tpu.ps.service import PSServer, RemoteEmbeddingStore
    from elasticdl_tpu.serving.client import ServingClient
    from elasticdl_tpu.serving.server import ServingServer

    spec = load_model_spec(
        "elasticdl_tpu.models", "deepfm.model_spec",
        buckets_per_feature=128, embedding_dim=4, hidden=(8,),
        host_tier=True,
    )
    table_key = next(iter(spec.host_io))
    ps = PSServer(spec.host_io, shard=0, num_shards=1).start()
    ckpt_dir = str(tmp_path / "ckpt")

    # Seed checkpoint (the serving template must restore, not fresh-init,
    # so the reload below swaps IDENTICAL dense params — isolating the
    # embedding-row effect).
    trainer = Trainer(
        spec, JobConfig(ps_addresses=ps.address),
        create_mesh([jax.devices()[0]]),
    )
    state = trainer.init_state(jax.random.key(0))
    mgr = CheckpointManager(ckpt_dir)
    mgr.save(0, jax.device_get(state), wait=True)
    mgr.publish(0)

    server = ServingServer(
        spec, checkpoint_dir=ckpt_dir, ps_addresses=ps.address,
        max_batch=4, max_delay_ms=2, poll_interval_s=0.05,
    ).start()
    client = ServingClient(server.address)
    try:
        server.warmup()
        client.wait_ready(10.0)
        feat = {
            "dense": np.zeros((1, 13), np.float32),
            "cat": np.arange(26, dtype=np.int64)[None, :] % 128,
        }
        before = client.predict_outputs(feat)[0]
        cache_stats = client.model_info()["cache"][table_key]
        assert cache_stats["misses"] > 0

        # Mutate the PS rows underneath (training pushing gradients): the
        # CACHE still serves the old rows — repeats are hits, same output.
        store = RemoteEmbeddingStore(table_key, spec.host_io[table_key].dim,
                                     [ps.address])
        ids = np.unique(
            spec.host_io[table_key].ids_fn(
                {k: np.asarray(v) for k, v in feat.items()}
            ).ravel()
        )
        rng_rows = np.ones((ids.size, store.dim), np.float32)
        for _ in range(50):  # adagrad steps push rows far from init
            store.push_grad(ids, rng_rows)
        store.close()
        mid = client.predict_outputs(feat)[0]
        assert mid == pytest.approx(before, abs=1e-5)  # cached rows served

        # Hot reload (same dense params, new publish): cache invalidated,
        # the next request pulls the PS's CURRENT rows -> output changes.
        mgr.save(1, jax.device_get(state), wait=True)
        mgr.publish(1)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if client.model_info()["step"] == 1:
                break
            time.sleep(0.05)
        assert client.model_info()["step"] == 1
        after = client.predict_outputs(feat)[0]
        assert abs(after - before) > 1e-4  # stale rows did not survive
        stats = client.model_info()["cache"][table_key]
        assert stats["invalidations"] >= 1
    finally:
        client.close()
        server.stop()
        mgr.close()
        ps.stop()


def test_serving_schemas_match_server_method_table():
    from elasticdl_tpu.common.rpc import SERVING_SCHEMAS

    # The method table lives in ServingServer.__init__; pin the contract
    # names so a server-side method add/remove must touch the schema too.
    assert set(SERVING_SCHEMAS) == {"Predict", "ModelInfo"}


def test_shed_surfaces_as_resource_exhausted_on_the_wire():
    """The caller contract everywhere (FleetServingClient never retries a
    shed; the fleet bench's bulk flood counts sheds by status) branches on
    RESOURCE_EXHAUSTED — a BatcherOverloaded escaping the Predict handler
    must map there at the generic-handler boundary, not surface as an
    unstructured UNKNOWN 'Exception calling application'."""
    from elasticdl_tpu.common.rpc import make_generic_handler

    def predict(req):
        raise BatcherOverloaded("queue holds 8 rows (bound 8); shedding")

    gh = make_generic_handler("test.Shed", {"Predict": predict})

    class _Details:
        method = "/test.Shed/Predict"

    handler = gh.service(_Details())

    class _Aborted(Exception):
        pass

    class _Ctx:
        code = None

        def abort(self, code, details):
            self.code = code
            raise _Aborted(details)  # real grpc abort() never returns

    ctx = _Ctx()
    with pytest.raises(_Aborted, match="shedding"):
        handler.unary_unary({"features": {}}, ctx)
    assert ctx.code == grpc.StatusCode.RESOURCE_EXHAUSTED
