from elasticdl_tpu.common.config import (
    DistributionStrategy,
    JobConfig,
    parse_args,
)


def test_defaults_valid():
    cfg = JobConfig()
    cfg.validate()


def test_parse_reference_style_flags():
    cfg = parse_args(
        [
            "--model_zoo", "elasticdl_tpu.models",
            "--model_def", "mnist.model_spec",
            "--distribution_strategy", "ParameterServer",
            "--minibatch_size", "128",
            "--num_epochs", "2",
            "--num_workers", "4",
            "--checkpoint_steps", "100",
        ]
    )
    assert cfg.distribution_strategy == DistributionStrategy.PARAMETER_SERVER
    assert cfg.minibatch_size == 128
    assert cfg.num_workers == 4


def test_json_roundtrip_env_bus():
    cfg = JobConfig(minibatch_size=256, job_name="j1")
    env = cfg.to_env()
    restored = JobConfig.from_env(env)
    assert restored == cfg


def test_invalid_strategy_rejected():
    import pytest

    cfg = JobConfig(distribution_strategy="Horovod")
    with pytest.raises(ValueError):
        cfg.validate()


def test_model_params_parsing():
    cfg = JobConfig(model_params="learning_rate=0.01;hidden=[64, 32];name=deep")
    parsed = cfg.parsed_model_params()
    assert parsed == {"learning_rate": 0.01, "hidden": [64, 32], "name": "deep"}


def test_learning_rate_flag_reaches_model():
    import optax

    from elasticdl_tpu.models import load_model_spec_for_job

    cfg = JobConfig(model_def="mnist.model_spec", learning_rate=0.5)
    spec = load_model_spec_for_job(cfg)
    # The optimizer must have been built with the flag's LR, not the default.
    params = {"w": __import__("jax.numpy", fromlist=["x"]).ones((2,))}
    state = spec.optimizer.init(params)
    grads = {"w": __import__("jax.numpy", fromlist=["x"]).ones((2,))}
    updates, _ = spec.optimizer.update(grads, state, params)
    assert abs(float(updates["w"][0])) == 0.5


def test_model_params_override_learning_rate_flag():
    from elasticdl_tpu.models import load_model_spec_for_job

    cfg = JobConfig(
        model_def="mnist.model_spec",
        learning_rate=0.5,
        model_params="learning_rate=0.25",
    )
    spec = load_model_spec_for_job(cfg)
    params = {"w": __import__("jax.numpy", fromlist=["x"]).ones((2,))}
    state = spec.optimizer.init(params)
    updates, _ = spec.optimizer.update(
        {"w": __import__("jax.numpy", fromlist=["x"]).ones((2,))}, state, params
    )
    assert abs(float(updates["w"][0])) == 0.25


def test_optimizer_sharding_knob_validation():
    import pytest

    JobConfig(optimizer_sharding="sharded").validate()
    JobConfig(optimizer_sharding="auto").validate()
    with pytest.raises(ValueError):
        JobConfig(optimizer_sharding="zero3").validate()
    with pytest.raises(ValueError):
        JobConfig(optimizer_sharding_auto_mb=0).validate()


def test_optimizer_sharding_flags_parse_and_roundtrip():
    cfg = parse_args(
        [
            "--optimizer_sharding", "auto",
            "--optimizer_sharding_auto_mb", "16.5",
            "--donate_train_state", "false",
        ]
    )
    assert cfg.optimizer_sharding == "auto"
    assert cfg.optimizer_sharding_auto_mb == 16.5
    assert cfg.donate_train_state is False
    assert JobConfig.from_env(cfg.to_env()) == cfg
