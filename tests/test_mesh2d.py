"""Hybrid-parallel 2D (data x model) mesh — r20.

The legal-shape resolver and dp_factorization's multi-axis behavior;
tensor-parallel transformer_lm parity against its own 1-D run; the
elastic 2D re-partitioner (re-lowers exactly once, shape-preserving
reforms add zero recompiles, moments carried bit-exactly); cross-shape
checkpoint restore; and the mesh-shape observability surface.
"""

import types

import jax
import numpy as np
import pytest

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.parallel import mesh as mesh_mod
from elasticdl_tpu.parallel.mesh import (
    create_mesh,
    dp_factorization,
    mesh_shape,
    resolve_2d_shape,
)
from elasticdl_tpu.parallel.trainer import Trainer
from elasticdl_tpu.models.spec import load_model_spec

SEQ = 32
VOCAB = 128


def _tp_spec(**kw):
    params = dict(
        compute_dtype="float32", vocab=VOCAB, dim=32, n_heads=4,
        n_layers=2, max_seq=SEQ, seq_len=SEQ, parallelism="tensor",
    )
    params.update(kw)
    return load_model_spec(
        "elasticdl_tpu.models", "transformer_lm.model_spec", **params
    )


def _batch(rng, b=8):
    toks = rng.integers(0, VOCAB, size=(b, SEQ + 1)).astype(np.int32)
    return {"tokens": toks[:, :-1], "labels": toks[:, 1:]}


# ---- legal-shape resolver ----


def test_resolve_2d_shape_prefers_shrinking_dp():
    """tp is a model-fit constraint: reform keeps it and shrinks dp;
    only when fewer than tp devices remain does tp degrade, and then
    only along the configured degree's divisor chain."""
    assert resolve_2d_shape(8, 4) == (2, 4)
    assert resolve_2d_shape(4, 4) == (1, 4)  # lost a host: dp 2 -> 1
    assert resolve_2d_shape(8, 2) == (4, 2)
    assert resolve_2d_shape(8, 1) == (8, 1)
    assert resolve_2d_shape(2, 4) == (1, 2)  # < tp devices: divisor chain
    assert resolve_2d_shape(3, 4) == (1, 2)
    assert resolve_2d_shape(1, 4) == (1, 1)
    # dp * tp may undershoot: the remainder idles, the axis stays regular.
    assert resolve_2d_shape(7, 2) == (3, 2)
    with pytest.raises(ValueError, match="at least one device"):
        resolve_2d_shape(0, 2)


def test_create_mesh_2d_axes_and_shape(devices):
    mesh = create_mesh(devices, num_devices=8, tensor_parallelism=4)
    assert mesh.axis_names == ("dp", "tp")
    assert dict(mesh.shape) == {"dp": 2, "tp": 4}
    assert mesh_shape(mesh) == (2, 4)
    # The (dp, tp) view is total over every mesh kind.
    assert mesh_shape(create_mesh(devices, num_devices=4)) == (4, 1)
    assert mesh_shape(
        create_mesh(devices, num_devices=8, dcn_parallelism=2)
    ) == (8, 1)
    with pytest.raises(ValueError, match="mutually exclusive"):
        create_mesh(
            devices, num_devices=8, dcn_parallelism=2, tensor_parallelism=2
        )
    with pytest.raises(ValueError, match="does not divide"):
        create_mesh(devices, num_devices=8, tensor_parallelism=3)


# ---- dp_factorization on multi-axis / exotic device orders ----


class _Dev:
    def __init__(self, process_index):
        self.process_index = process_index


def _stub_mesh(grid, axis_names):
    return types.SimpleNamespace(
        devices=np.array(grid, dtype=object), axis_names=axis_names
    )


def _procs(*indexes):
    return [_Dev(i) for i in indexes]


def test_dp_factorization_multi_axis_process_pairs():
    """The dp axis of a (dp, tp) mesh whose positions are owned by
    disjoint process GROUPS factors by those groups — each dp row is one
    'host' of the hierarchy."""
    row0 = _procs(0, 0, 1, 1)  # dp row 0: processes {0, 1}
    row1 = _procs(2, 2, 3, 3)  # dp row 1: processes {2, 3}
    mesh = _stub_mesh([row0, row1], ("dp", "tp"))
    assert dp_factorization(mesh) == (2, 1)


def test_dp_factorization_contiguous_1d():
    mesh = _stub_mesh(_procs(0, 0, 0, 0, 1, 1, 1, 1), ("dp",))
    assert dp_factorization(mesh) == (2, 4)


def test_dp_factorization_ragged_demotes_silently(monkeypatch):
    """Unequal per-process runs have no clean hierarchy: flat (1, n),
    and — single-owner positions — without the multi-axis warning."""
    warned = []
    monkeypatch.setattr(
        mesh_mod.logger, "warning", lambda *a, **k: warned.append(a)
    )
    mesh = _stub_mesh(_procs(0, 0, 0, 1), ("dp",))
    assert dp_factorization(mesh) == (1, 4)
    assert not warned


def test_dp_factorization_tp_major_demotes_loudly(monkeypatch):
    """A tp-major order threads every process through every dp position
    (owner sets identical along the axis): a real host hierarchy is
    being hidden by the device order, so the demotion to flat WARNS."""
    warned = []
    monkeypatch.setattr(
        mesh_mod.logger, "warning", lambda *a, **k: warned.append(a)
    )
    row0 = _procs(0, 1)  # dp position 0 spans BOTH processes...
    row1 = _procs(0, 1)  # ...and so does position 1: no grouping.
    mesh = _stub_mesh([row0, row1], ("dp", "tp"))
    assert dp_factorization(mesh) == (1, 2)
    assert warned


def test_dp_factorization_overlapping_groups_demote_loudly(monkeypatch):
    """Owner groups that re-use a process across runs overlap — equally
    sized runs are not enough; the union must be disjoint."""
    warned = []
    monkeypatch.setattr(
        mesh_mod.logger, "warning", lambda *a, **k: warned.append(a)
    )
    mesh = _stub_mesh(
        [_procs(0, 1), _procs(1, 2)], ("dp", "tp")
    )
    assert dp_factorization(mesh) == (1, 2)
    assert warned


def test_dp_factorization_single_process_2d(devices):
    """The real fake-device world is single-process: the dp axis of a
    live (dp, tp) mesh demotes to flat quietly (nothing to exploit)."""
    mesh = create_mesh(devices, num_devices=8, tensor_parallelism=4)
    assert dp_factorization(mesh) == (1, 2)


# ---- tensor-parallel parity ----


def test_tensor_parallel_matches_1d(devices):
    """Column/row-split attention + MLP through the tp psum reproduce the
    dense math: same spec, same batches, 1-D dp=2 vs 2-D (dp=2, tp=2) —
    losses within float32 reduction-order noise for the ISSUE's 1e-6 bar."""
    cfg = JobConfig(distribution_strategy="AllReduce")
    t2 = Trainer(_tp_spec(), cfg,
                 create_mesh(devices, num_devices=4, tensor_parallelism=2))
    t1 = Trainer(_tp_spec(), cfg, create_mesh(devices, num_devices=2))
    s2 = t2.init_state(jax.random.key(0))
    s1 = t1.init_state(jax.random.key(0))
    rng = np.random.default_rng(0)
    for _ in range(4):
        host = _batch(rng)
        s2, m2 = t2.train_step(s2, t2.shard_batch(host))
        s1, m1 = t1.train_step(s1, t1.shard_batch(host))
        assert abs(float(m2["loss"]) - float(m1["loss"])) <= 1e-6
    for a, b in zip(
        jax.tree.leaves(jax.device_get(s2.params)),
        jax.tree.leaves(jax.device_get(s1.params)),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6
        )


def test_tp_weights_are_sharded_and_bytes_drop(devices):
    """The declared tensor_sharding lands in the placement (column
    matrices split over tp dim 1, row matrices dim 0, norms replicated),
    and the analytic grad-reduce bytes fall vs the 1-D layout — each rank
    reduces only its 1/tp shard over dp."""
    from jax.sharding import PartitionSpec as P

    cfg = JobConfig(distribution_strategy="AllReduce")
    t2 = Trainer(_tp_spec(), cfg,
                 create_mesh(devices, num_devices=4, tensor_parallelism=2))
    s2 = t2.init_state(jax.random.key(0))
    blk = s2.params["blocks"]["b0"]
    assert blk["wqkv"].sharding.spec == P(None, "tp")
    assert blk["w1"].sharding.spec == P(None, "tp")
    assert blk["wo"].sharding.spec == P("tp", None)
    assert blk["w2"].sharding.spec == P("tp", None)
    assert blk["ln1"].sharding.spec == P()

    t1 = Trainer(_tp_spec(), cfg, create_mesh(devices, num_devices=2))
    s1 = t1.init_state(jax.random.key(0))
    b2 = t2.collective_bytes_per_step(s2)
    b1 = t1.collective_bytes_per_step(s1)
    assert b2["resolved"] < b1["resolved"]


# ---- the elastic 2D re-partitioner ----


def test_2d_reform_relowers_once_and_carries_moments(devices):
    """Every re-partition — 2D -> smaller 2D -> back, and 2D -> 1D —
    bridges the sharded Adam moments bit-exactly through the canonical
    host layout, and trainer.train_step re-lowers exactly ONCE per
    topology (jitsan v6 counters; repeat steps add zero)."""
    from elasticdl_tpu.common import jitsan

    cfg = JobConfig(
        distribution_strategy="AllReduce", optimizer_sharding="sharded"
    )
    t = Trainer(_tp_spec(), cfg,
                create_mesh(devices, num_devices=8, tensor_parallelism=4))
    state = t.init_state(jax.random.key(0))
    rng = np.random.default_rng(1)
    c0 = jitsan.compiles("trainer.train_step")
    for _ in range(2):
        state, _ = t.train_step(state, t.shard_batch(_batch(rng)))
    if jitsan.enabled():
        assert jitsan.compiles("trainer.train_step") == c0 + 1

    def reshard(mesh):
        before = jax.device_get(t.host_state(state))
        t.set_mesh(mesh)
        placed = t.shard_state(before)
        after = jax.device_get(t.host_state(placed))
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        return placed

    # (dp2, tp4) -> lose a host -> (dp1, tp4): tp preserved.
    state = reshard(create_mesh(devices, num_devices=4, tensor_parallelism=4))
    assert mesh_shape(t.mesh) == (1, 4)
    state, m = t.train_step(state, t.shard_batch(_batch(rng)))
    assert np.isfinite(float(m["loss"]))
    state, _ = t.train_step(state, t.shard_batch(_batch(rng)))
    if jitsan.enabled():
        assert jitsan.compiles("trainer.train_step") == c0 + 2

    # Back to (dp2, tp4), carrying the steps trained at (1, 4).
    state = reshard(create_mesh(devices, num_devices=8, tensor_parallelism=4))
    state, _ = t.train_step(state, t.shard_batch(_batch(rng)))
    if jitsan.enabled():
        assert jitsan.compiles("trainer.train_step") == c0 + 3

    # The 2D -> 1D re-partition: tensor mode on a flat mesh runs dense.
    state = reshard(create_mesh(devices, num_devices=4))
    assert mesh_shape(t.mesh) == (4, 1)
    state, m = t.train_step(state, t.shard_batch(_batch(rng)))
    assert int(state.step) == 6 and np.isfinite(float(m["loss"]))
    if jitsan.enabled():
        assert jitsan.compiles("trainer.train_step") == c0 + 4


def test_shape_preserving_reform_adds_zero_recompiles(tmp_path, devices):
    """The worker's identical-topology guard holds on the 2D path: a
    membership version bump that keeps ranks+addresses adopts WITHOUT
    set_mesh, so no re-lower and no state churn; a genuine world change
    re-forms to the resolved legal 2D shape exactly once."""
    from elasticdl_tpu.common import jitsan
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.worker.worker import Worker

    path = str(tmp_path / "lm.rio")
    generate("lm", path, 8, seq_len=SEQ, vocab=VOCAB)
    config = JobConfig(
        model_def="transformer_lm.model_spec", training_data=path,
        minibatch_size=8, tensor_parallelism=2,
    )
    worker = Worker(
        config, master=None, reader=create_data_reader(path),
        spec=_tp_spec(), devices=devices, devices_per_worker=4,
    )
    worker._apply_membership(
        {"version": 0, "world_size": 1, "ranks": {"w": 0}}, initial=True
    )
    assert mesh_shape(worker.trainer.mesh) == (2, 2)
    worker.state = worker.trainer.init_state(jax.random.key(0))
    rng = np.random.default_rng(0)
    t = worker.trainer
    worker.state, _ = t.train_step(worker.state, t.shard_batch(_batch(rng)))
    c1 = jitsan.compiles("trainer.train_step")

    # Version churn, identical topology: adopt, don't re-form.
    worker._apply_membership(
        {"version": 1, "world_size": 1, "ranks": {"w": 0}}
    )
    assert worker.reforms == 0 and worker.trainer is t
    worker.state, _ = t.train_step(worker.state, t.shard_batch(_batch(rng)))
    assert jitsan.compiles("trainer.train_step") == c1  # zero recompiles

    # A real join doubles the world: reform to the legal (dp4, tp2).
    worker._apply_membership(
        {"version": 2, "world_size": 2, "ranks": {"w": 0, "x": 1}}
    )
    assert worker.reforms == 1
    assert mesh_shape(worker.trainer.mesh) == (4, 2)
    worker.state, m = worker.trainer.train_step(
        worker.state, worker.trainer.shard_batch(_batch(rng))
    )
    assert np.isfinite(float(m["loss"]))
    if jitsan.enabled():
        assert jitsan.compiles("trainer.train_step") == c1 + 1


def test_worker_publishes_mesh_shape_gauge(tmp_path, devices):
    """edl_mesh_shape{axis=dp|tp} rides the worker's registry, and
    watch_job renders the pair as one ``mesh: dpNxtpM`` line."""
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.worker.worker import Worker
    from tools.watch_job import render_mesh

    path = str(tmp_path / "lm.rio")
    generate("lm", path, 8, seq_len=SEQ, vocab=VOCAB)
    config = JobConfig(
        model_def="transformer_lm.model_spec", training_data=path,
        minibatch_size=8, tensor_parallelism=4,
    )
    worker = Worker(
        config, master=None, reader=create_data_reader(path),
        spec=_tp_spec(), devices=devices, devices_per_worker=8,
    )
    worker._apply_membership(
        {"version": 0, "world_size": 1, "ranks": {"w": 0}}, initial=True
    )
    snap = worker.gauges.snapshot()
    fam = snap["edl_mesh_shape"]
    by_axis = {
        dict(s["labels"])["axis"]: s["value"] for s in fam["samples"]
    }
    assert by_axis == {"dp": 2.0, "tp": 4.0}
    assert render_mesh({"edl_mesh_shape": fam}) == "mesh: dp2xtp4"


# ---- cross-shape checkpoint restore ----


def test_checkpoint_restores_across_2d_shapes(tmp_path, devices):
    """A 4x2-sharded save (tp-major: dp=2, tp=4) restores bit-exactly —
    dense params AND canonical moments — into (2, 2), (1, 4) and the 1-D
    dp=4 mesh, and trains on each target topology."""
    from elasticdl_tpu.common.checkpoint import CheckpointManager

    cfg = JobConfig(
        distribution_strategy="AllReduce", optimizer_sharding="sharded"
    )
    spec = _tp_spec()
    t8 = Trainer(spec, cfg,
                 create_mesh(devices, num_devices=8, tensor_parallelism=4))
    state = t8.init_state(jax.random.key(0))
    rng = np.random.default_rng(2)
    for _ in range(2):
        state, _ = t8.train_step(state, t8.shard_batch(_batch(rng)))
    canonical = t8.host_state(state)

    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    ckpt.save(2, canonical, wait=True)

    targets = (
        create_mesh(devices, num_devices=4, tensor_parallelism=2),  # (2, 2)
        create_mesh(devices, num_devices=4, tensor_parallelism=4),  # (1, 4)
        create_mesh(devices, num_devices=4),                        # 1-D dp4
    )
    for mesh in targets:
        t = Trainer(spec, cfg, mesh)
        template = t.init_state(jax.random.key(1))  # different init
        restored = t.adopt_restored(
            ckpt.restore(t.restore_template(template))
        )
        assert int(restored.step) == 2
        got = t.host_state(restored)
        for a, b in zip(jax.tree.leaves(canonical), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        state_t, m = t.train_step(restored, t.shard_batch(_batch(rng)))
        assert int(state_t.step) == 3
        assert np.isfinite(float(m["loss"]))
    ckpt.close()
