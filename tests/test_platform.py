"""The killable-subprocess device probe (VERDICT r4 Weak #1 / Next #1).

The twice-recorded chip failure mode is a *hang* inside ``jax.devices()``
(BENCH_r02/r04: phase "init" burned the whole watchdog).  The probe's job is
to make that survivable: bounded killable attempts, success string on a live
backend, RuntimeError (not a hang) when the backend never answers.
"""

from __future__ import annotations

import logging

import pytest

from elasticdl_tpu.common import platform


def test_probe_succeeds_on_live_backend():
    # The subprocess inherits JAX_PLATFORMS=cpu from conftest, so it answers
    # quickly with the fake-CPU device count.
    summary = platform.probe_devices(attempts=2, timeout_s=120.0)
    n, plat = summary.split()
    assert int(n) >= 1
    assert plat == "cpu"


def test_probe_hang_is_killed_and_bounded(monkeypatch, caplog):
    # Simulate the observed failure: the probe process never answers.  Each
    # attempt must be killed at timeout_s and the call must raise instead of
    # hanging.
    monkeypatch.setattr(platform, "_PROBE_CODE", "import time; time.sleep(60)")
    seen = []
    with pytest.raises(RuntimeError, match="probe failed 2x"):
        platform.probe_devices(
            attempts=2, timeout_s=0.5, backoff_s=0.0, log=seen.append
        )
    assert len(seen) == 2
    assert all("hung" in m for m in seen)


def test_probe_crash_is_retried_then_raises(monkeypatch):
    monkeypatch.setattr(
        platform, "_PROBE_CODE", "import sys; sys.stderr.write('boom'); sys.exit(3)"
    )
    seen = []
    with pytest.raises(RuntimeError, match="boom"):
        platform.probe_devices(
            attempts=2, timeout_s=10.0, backoff_s=0.0, log=seen.append
        )
    assert len(seen) == 2
    assert all("boom" in m for m in seen)
