"""common/racesan.py: the runtime shared-state sanitizer must catch a
seeded cross-role unguarded write DETERMINISTICALLY (observation-based,
not timing-based: the threads are fully sequenced by join and the second
write still trips) and stay silent on the lock-guarded clean twin.
Tier-1 runs with GRAFT_RACESAN=1 (tests/conftest.py), so the opted-in
control-plane classes (PodManager, RendezvousServer, CheckpointWatcher)
are live-checked in every suite that exercises them."""

import os
import threading

import pytest

from elasticdl_tpu.common import locksan, racesan


@pytest.fixture(autouse=True)
def _fresh_locksan():
    locksan.reset()
    yield
    locksan.reset()


def _run_as(role_name, fn):
    """Run ``fn`` on a named thread; return the exception it raised (or
    None).  join() sequences the threads completely — no timing games."""
    box = [None]

    def wrapper():
        try:
            fn()
        except Exception as e:  # noqa: BLE001 - the test inspects it
            box[0] = e

    t = threading.Thread(target=wrapper, name=role_name, daemon=True)
    t.start()
    t.join(10.0)
    assert not t.is_alive(), "racesan test thread wedged"
    return box[0]


def test_suite_runs_sanitized():
    assert os.environ.get("GRAFT_RACESAN") == "1"
    assert racesan.enabled()


def test_cross_role_unguarded_write_raises_deterministically():
    @racesan.instrument
    class C:
        def __init__(self):
            self.x = 0

    c = C()

    err = _run_as("roleA", lambda: setattr(c, "x", 1))
    assert err is None  # first post-init write: nothing to conflict with
    err = _run_as("roleB", lambda: setattr(c, "x", 2))
    assert isinstance(err, racesan.RaceSanViolation)
    assert "roleA" in str(err) and "roleB" in str(err)
    assert "C.x" in str(err)


def test_clean_twin_common_lock():
    lock = locksan.lock("RaceClean._lock")

    @racesan.instrument
    class C:
        def __init__(self):
            self.x = 0

    c = C()

    def write_locked(v):
        with lock:
            c.x = v

    assert _run_as("roleA", lambda: write_locked(1)) is None
    assert _run_as("roleB", lambda: write_locked(2)) is None
    assert c.__dict__["x"] == 2


def test_read_then_cross_role_write_raises():
    @racesan.instrument
    class C:
        def __init__(self):
            self.x = 0

    c = C()

    def read_many():
        # Sampled reads: loop past the sampling period so at least one
        # observation lands.
        for _ in range(64):
            _ = c.x

    assert _run_as("reader", read_many) is None
    err = _run_as("writer", lambda: setattr(c, "x", 1))
    assert isinstance(err, racesan.RaceSanViolation)
    assert "reader" in str(err)


def test_init_writes_are_exempt():
    @racesan.instrument
    class C:
        def __init__(self):
            self.x = 0  # construction happens-before publication

    c = C()
    # The FIRST post-init write from another role must not conflict with
    # the construction-time write.
    assert _run_as("other", lambda: setattr(c, "x", 1)) is None


def test_single_writer_declaration_enforced():
    @racesan.instrument(single_writer={"step": "driver"})
    class C:
        def __init__(self):
            self.step = 0

    c = C()
    assert _run_as("driver", lambda: setattr(c, "step", 1)) is None
    err = _run_as("intruder", lambda: setattr(c, "step", 2))
    assert isinstance(err, racesan.RaceSanViolation)
    assert "single-writer" in str(err) and "driver" in str(err)


def test_atomic_attrs_exempt():
    @racesan.instrument(atomic=("last",))
    class C:
        def __init__(self):
            self.last = 0.0

    c = C()
    assert _run_as("roleA", lambda: setattr(c, "last", 1.0)) is None
    assert _run_as("roleB", lambda: setattr(c, "last", 2.0)) is None


def test_instance_confinement_no_false_positive():
    # Two instances, each touched by ONE role: observations are
    # per-instance, so neither trips (the static pass's documented
    # instance-confinement blind spot, closed here).
    @racesan.instrument
    class C:
        def __init__(self):
            self.x = 0

    a, b = C(), C()
    assert _run_as("roleA", lambda: setattr(a, "x", 1)) is None
    assert _run_as("roleB", lambda: setattr(b, "x", 1)) is None
    assert _run_as("roleA", lambda: setattr(a, "x", 2)) is None
    assert _run_as("roleB", lambda: setattr(b, "x", 2)) is None


def test_thread_role_inference_and_override():
    roles = {}

    def record(key):
        roles[key] = racesan.thread_role()

    assert _run_as("edl-ingest_3", lambda: record("pool")) is None
    assert roles["pool"] == "edl-ingest"
    assert _run_as("Thread-12", lambda: record("anon")) is None
    assert roles["anon"] == "Thread"
    record("main")
    assert roles["main"] == "main"

    def overridden():
        racesan.set_role("grpc:Test")
        record("explicit")

    assert _run_as("whatever-7", overridden) is None
    assert roles["explicit"] == "grpc:Test"


def test_disabled_mode_is_identity(monkeypatch):
    monkeypatch.setenv("GRAFT_RACESAN", "0")

    @racesan.instrument
    class C:
        def __init__(self):
            self.x = 0

    assert not hasattr(C, "_racesan_instrumented")
    c = C()
    assert "_racesan_obs" not in c.__dict__  # plain attribute semantics
    assert _run_as("roleA", lambda: setattr(c, "x", 1)) is None
    assert _run_as("roleB", lambda: setattr(c, "x", 2)) is None


def test_opted_in_control_plane_classes_are_instrumented():
    from elasticdl_tpu.master.pod_manager import PodManager
    from elasticdl_tpu.master.rendezvous import RendezvousServer
    from elasticdl_tpu.serving.checkpoint_watcher import CheckpointWatcher

    for cls in (PodManager, RendezvousServer, CheckpointWatcher):
        assert getattr(cls, "_racesan_instrumented", False), cls


def test_single_writer_tolerates_cross_role_readers():
    # The declared contract: one role writes, other roles read GIL-atomic
    # loads.  A sampled cross-role read must NOT make the next legal
    # write raise (it records, but the declared writer skips the
    # lock-based cross-role check).
    @racesan.instrument(single_writer={"step": "driver"})
    class C:
        def __init__(self):
            self.step = 0

    c = C()

    def read_many():
        for _ in range(64):
            _ = c.step

    assert _run_as("reader", read_many) is None
    assert _run_as("driver", lambda: setattr(c, "step", 1)) is None
    assert _run_as("driver", lambda: setattr(c, "step", 2)) is None
    assert c.__dict__["step"] == 2


def test_subclass_init_writes_are_construction():
    # A subclass __init__ keeps writing after super().__init__() returns;
    # those are still construction (pre-publication) writes and must not
    # seed observations that a later legitimate hand-off write trips on.
    @racesan.instrument
    class P:
        def __init__(self):
            self.x = 0

    class Child(P):
        def __init__(self):
            super().__init__()
            self.y = 1  # after the instrumented __init__ returned

    c = Child()
    c.y = 2  # constructing thread, still pre-publication: construction
    assert _run_as("other", lambda: setattr(c, "y", 3)) is None
