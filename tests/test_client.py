"""CLI client: verb dispatch, manifest rendering, zoo init/build, and a full
`elasticdl train --local`-equivalent job through the client API (the
reference's client->master submission path, SURVEY.md §3.1, run in-process)."""

import json
import subprocess
import sys

import pytest

from elasticdl_tpu.client import api, zoo
from elasticdl_tpu.client.main import main as cli_main
from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.data.synthetic import generate


def test_cli_usage_and_unknown_verb():
    assert cli_main([]) == 2
    assert cli_main(["frobnicate"]) == 2
    assert cli_main(["--help"]) == 0


def test_master_manifest_render():
    config = JobConfig(job_name="j1", training_data="/data/x.rio")
    m = api.render_master_pod_manifest(config, image="zoo:v2")
    assert m["metadata"]["name"] == "j1-master"
    container = m["spec"]["containers"][0]
    assert container["image"] == "zoo:v2"
    assert container["command"] == ["python", "-m", "elasticdl_tpu.master.main"]
    env = {e["name"]: e["value"] for e in container["env"] if "value" in e}
    # the downward-API pod IP the master advertises to workers
    assert any(e["name"] == "MY_POD_IP" and "valueFrom" in e for e in container["env"])
    roundtrip = JobConfig.from_json(env["ELASTICDL_JOB_CONFIG"])
    assert roundtrip.job_name == "j1"
    assert roundtrip.training_data == "/data/x.rio"


def test_submit_writes_manifest(tmp_path):
    out = str(tmp_path / "master.json")
    config = JobConfig(job_name="j2", training_data="/data/x.rio")
    api.submit(config, manifest_out=out)
    with open(out) as f:
        manifest = json.load(f)
    assert manifest["metadata"]["labels"]["elasticdl-replica-type"] == "master"


def test_cli_train_manifest_out(tmp_path):
    out = str(tmp_path / "m.json")
    rc = cli_main(
        [
            "train",
            "--job_name=cli-job",
            "--training_data=/data/t.rio",
            f"--manifest_out={out}",
        ]
    )
    assert rc == 0
    with open(out) as f:
        manifest = json.load(f)
    env = {
        e["name"]: e["value"]
        for e in manifest["spec"]["containers"][0]["env"]
        if "value" in e
    }
    cfg = JobConfig.from_json(env["ELASTICDL_JOB_CONFIG"])
    assert cfg.job_type == "training"
    assert cfg.job_name == "cli-job"


def test_zoo_init_build_cycle(tmp_path):
    zoo_dir = str(tmp_path / "myzoo")
    zoo.zoo_init(zoo_dir)
    specs, import_failures = zoo.discover_model_specs(zoo_dir)
    assert any("template" in k for k in specs)
    assert import_failures == []
    assert zoo.zoo_build(zoo_dir, validate_only=True) == 0
    # init is idempotent: re-running keeps existing files
    zoo.zoo_init(zoo_dir)


def test_zoo_build_reports_bad_model(tmp_path):
    zoo_dir = tmp_path / "badzoo"
    zoo_dir.mkdir()
    (zoo_dir / "__init__.py").write_text("")
    (zoo_dir / "broken.py").write_text(
        "def model_spec():\n    return object()\n"
    )
    assert zoo.zoo_build(str(zoo_dir), validate_only=True) == 1


def test_zoo_build_reports_import_error(tmp_path):
    zoo_dir = tmp_path / "importzoo"
    zoo_dir.mkdir()
    (zoo_dir / "__init__.py").write_text("")
    (zoo_dir / "broken.py").write_text("import nonexistent_pkg_xyz\n")
    (zoo_dir / "ok.py").write_text(
        "from elasticdl_tpu.models.mnist import model_spec\n"
    )
    # broken module is reported, but the good module still validates
    failures = zoo.validate_zoo(str(zoo_dir))
    assert any("import failed" in err for _, err in failures)
    assert zoo.zoo_build(str(zoo_dir), validate_only=True) == 1


def test_zoo_build_empty_dir(tmp_path):
    empty = tmp_path / "empty"
    empty.mkdir()
    assert zoo.zoo_build(str(empty), validate_only=True) == 1


@pytest.mark.slow
def test_cli_local_train_job(tmp_path):
    """`elasticdl train` local mode end-to-end: client -> in-process master ->
    subprocess worker pods (the whole stack, one host)."""
    train_path = str(tmp_path / "train.rio")
    generate("mnist", train_path, 64)
    ckpt = str(tmp_path / "ckpt")
    rc = cli_main(
        [
            "train",
            "--local",
            "--job_name=cli-local",
            "--model_def=mnist.model_spec",
            "--model_params=compute_dtype=float32",
            f"--training_data={train_path}",
            "--minibatch_size=16",
            "--num_minibatches_per_task=2",
            "--num_workers=1",
            f"--checkpoint_dir={ckpt}",
            "--checkpoint_steps=2",
        ]
    )
    assert rc == 0


def test_console_script_entry():
    """python -m elasticdl_tpu.client.main prints usage without a cluster."""
    proc = subprocess.run(
        [sys.executable, "-m", "elasticdl_tpu.client.main", "--help"],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0
    assert "train|evaluate|predict" in proc.stderr
