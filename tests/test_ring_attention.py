"""Ring attention vs full attention: exactness (causal and not), gradients,
and degenerate single-device behavior — on the 8-fake-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.ops.ring_attention import attention_reference, ring_attention
from elasticdl_tpu.parallel.mesh import create_mesh

from elasticdl_tpu.common.jax_compat import shard_map

B, L, H, D = 2, 64, 4, 16


def _qkv(seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return (
        jax.random.normal(ks[0], (B, L, H, D), jnp.float32),
        jax.random.normal(ks[1], (B, L, H, D), jnp.float32),
        jax.random.normal(ks[2], (B, L, H, D), jnp.float32),
    )


def _ring(mesh, causal):
    axis = mesh.axis_names[0]
    spec = P(None, axis)  # shard the sequence axis

    def fn(q, k, v):
        return ring_attention(q, k, v, axis_name=axis, causal=causal)

    mapped = shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, spec))
    return mapped, sh


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_ring_matches_full(devices, causal, n_dev):
    mesh = create_mesh(devices, num_devices=n_dev, axis_name="sp")
    q, k, v = _qkv()
    expected = attention_reference(q, k, v, causal=causal)
    mapped, sh = _ring(mesh, causal)
    out = jax.jit(mapped)(sh(q), sh(k), sh(v))
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-5
    )


def test_ring_gradients_match(devices):
    mesh = create_mesh(devices, num_devices=4, axis_name="sp")
    q, k, v = _qkv(1)
    cot = jax.random.normal(jax.random.key(9), (B, L, H, D))

    ref_grads = jax.grad(
        lambda q, k, v: jnp.sum(attention_reference(q, k, v, causal=True) * cot),
        argnums=(0, 1, 2),
    )(q, k, v)

    axis = mesh.axis_names[0]
    spec = P(None, axis)

    def local_loss(q, k, v, c):
        return jnp.sum(ring_attention(q, k, v, axis_name=axis, causal=True) * c)

    mapped = shard_map(
        jax.grad(local_loss, argnums=(0, 1, 2)),
        mesh=mesh,
        in_specs=(spec,) * 4,
        out_specs=(spec,) * 3,
        check_vma=False,
    )
    sh = lambda a: jax.device_put(a, NamedSharding(mesh, spec))
    grads = jax.jit(mapped)(sh(q), sh(k), sh(v), sh(cot))
    for got, want in zip(grads, ref_grads):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-4
        )


def test_no_axis_is_plain_attention():
    q, k, v = _qkv(2)
    out = ring_attention(q, k, v, axis_name=None, causal=True)
    expected = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)


def test_causal_first_token_attends_self_only(devices):
    """Position 0 must see only itself: its output is v[0] exactly."""
    mesh = create_mesh(devices, num_devices=4, axis_name="sp")
    q, k, v = _qkv(3)
    mapped, sh = _ring(mesh, True)
    out = jax.jit(mapped)(sh(q), sh(k), sh(v))
    np.testing.assert_allclose(
        np.asarray(out)[:, 0], np.asarray(v)[:, 0], rtol=1e-5
    )
