"""Sequence-parallel transformer LM: the ring-attention op integrated into a
trainable model family (previously the op was test-only).  The mesh axis
shards the SEQUENCE dim (ModelSpec.batch_shard_dim=1); parity is asserted
against the identical model run unsharded."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.parallel.trainer import Trainer

SEQ = 64
VOCAB = 512


def _spec(**kw):
    params = dict(
        compute_dtype="float32",
        vocab=VOCAB,
        dim=64,
        n_heads=4,
        n_layers=2,
        max_seq=SEQ,
        seq_len=SEQ,
    )
    params.update(kw)
    return load_model_spec(
        "elasticdl_tpu.models", "transformer_lm.model_spec", **params
    )


def _batch(rng, b=4):
    toks = rng.integers(0, VOCAB, size=(b, SEQ + 1)).astype(np.int32)
    return {
        "tokens": toks[:, :-1],
        "labels": toks[:, 1:],
    }


def test_sequence_parallel_matches_unsharded(devices):
    """Forward loss and one train step over an 8-way sequence-sharded mesh
    equal the 1-device run bit-for-bit-ish (fp tolerance): ring attention +
    global positions + psum'd grads reproduce full attention."""
    spec8, spec1 = _spec(), _spec()
    rng = np.random.default_rng(0)
    batch = _batch(rng)
    cfg = JobConfig(distribution_strategy="AllReduce")

    tr8 = Trainer(spec8, cfg, create_mesh(devices, num_devices=8))
    tr1 = Trainer(spec1, cfg, create_mesh(devices, num_devices=1))
    s8 = tr8.init_state(jax.random.key(0))
    s1 = tr1.init_state(jax.random.key(0))

    s8, m8 = tr8.run_train_step(s8, batch)
    s1, m1 = tr1.run_train_step(s1, batch)
    np.testing.assert_allclose(float(m8["loss"]), float(m1["loss"]), rtol=1e-5)
    np.testing.assert_allclose(
        float(m8["accuracy"]), float(m1["accuracy"]), rtol=1e-6
    )
    # params after the update agree too (grads were identical)
    p8 = jax.device_get(s8).params
    p1 = jax.device_get(s1).params
    for k8, k1 in zip(jax.tree.leaves(p8), jax.tree.leaves(p1)):
        np.testing.assert_allclose(np.asarray(k8), np.asarray(k1),
                                   rtol=2e-4, atol=2e-5)


def test_lm_learns_planted_rule(devices, tmp_path):
    """End-to-end: synthetic LM data with a planted next-token rule; training
    over the sequence-sharded mesh drives loss far below uniform."""
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.data.synthetic import generate

    path = str(tmp_path / "lm.rio")
    generate("lm", path, 64, seq_len=SEQ, vocab=VOCAB)
    reader = create_data_reader(path)
    records = list(reader.read_records(reader.create_shards(64)[0]))
    spec = _spec(learning_rate=3e-3)
    batch = spec.feed(records)

    tr = Trainer(spec, JobConfig(distribution_strategy="AllReduce"),
                 create_mesh(devices))
    state = tr.init_state(jax.random.key(0))
    losses = []
    for _ in range(80):
        state, metrics = tr.run_train_step(state, batch)
        losses.append(float(metrics["loss"]))
    uniform = float(np.log(VOCAB))
    assert losses[0] > uniform * 0.8  # starts near uniform
    assert losses[-1] < uniform * 0.5, losses[-5:]  # learned the rule


def test_lm_eval_and_predict_shapes(devices):
    spec = _spec()
    tr = Trainer(spec, JobConfig(distribution_strategy="AllReduce"),
                 create_mesh(devices))
    state = tr.init_state(jax.random.key(0))
    batch = _batch(np.random.default_rng(1))
    metrics = tr.run_eval_step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    out = np.asarray(tr.run_predict_step(state, batch))
    assert out.shape == (4, SEQ, VOCAB)


def test_seq_not_divisible_raises(devices):
    spec = _spec()
    tr = Trainer(spec, JobConfig(distribution_strategy="AllReduce"),
                 create_mesh(devices))
    bad = {"tokens": np.zeros((4, 60), np.int32),
           "labels": np.zeros((4, 60), np.int32)}
    with pytest.raises(ValueError, match="dimension 1"):
        tr.shard_batch(bad)


def test_over_long_sequence_fails_loud(devices):
    """Positions past max_seq must raise, not silently clamp on the pos_emb
    gather (the repo's fail-loud stance)."""
    spec = _spec(max_seq=32)  # < SEQ=64
    tr = Trainer(spec, JobConfig(distribution_strategy="AllReduce"),
                 create_mesh(devices))
    state = tr.init_state(jax.random.key(0))
    with pytest.raises(ValueError, match="max_seq"):
        tr.run_train_step(state, _batch(np.random.default_rng(0)))


def test_remat_matches_no_remat(devices):
    """jax.checkpoint per block (remat=True, the default) changes only WHEN
    activations are computed, never the values: losses and a full train step
    match the remat=False lowering across the sequence-sharded mesh."""

    def run(remat):
        spec = _spec(remat=remat)
        tr = Trainer(spec, JobConfig(distribution_strategy="AllReduce"),
                     create_mesh(devices))
        state = tr.init_state(jax.random.key(0))
        losses = []
        for s in range(2):
            batch = _batch(np.random.default_rng(s))
            state, m = tr.run_train_step(state, batch)
            losses.append(float(m["loss"]))
        return losses, jax.device_get(state.params["blocks"]["b0"]["wqkv"])

    on_losses, on_w = run(True)
    off_losses, off_w = run(False)
    np.testing.assert_allclose(on_losses, off_losses, rtol=1e-6)
    # atol floor 2e-6: remat changes the fusion boundaries XLA:CPU picks,
    # and the two lowerings legitimately differ by ~1 ulp-chain on a handful
    # of weights after the optimizer update — identity is the wrong bar.
    np.testing.assert_allclose(on_w, off_w, rtol=1e-6, atol=2e-6)


def test_worker_fused_task_with_sequence_parallelism(tmp_path, devices):
    """The r4 fused whole-task path (stacked batch + lax.scan) must work for
    SEQUENCE-parallel models too: stacked leaves gain a leading scan dim, so
    the sequence dim shards from position 2."""
    import jax
    import numpy as np

    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.data.reader import Shard, create_data_reader
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.task_dispatcher import TASK_TRAINING, Task
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import Worker

    path = str(tmp_path / "lm.rio")
    generate("lm", path, 16, seq_len=64, vocab=128)
    spec = load_model_spec(
        "elasticdl_tpu.models", "transformer_lm.model_spec",
        vocab=128, seq_len=64, dim=32, n_heads=2, n_layers=1,
        compute_dtype="float32",
    )
    config = JobConfig(
        model_def="transformer_lm.model_spec", training_data=path,
        minibatch_size=4,
    )
    reader = create_data_reader(path)
    worker = Worker(
        config, master=None, reader=reader, spec=spec, devices=devices
    )
    worker._apply_membership(
        {"version": 0, "world_size": 1, "ranks": {"w": 0}}, initial=True
    )
    worker.state = worker.trainer.init_state(jax.random.key(0))
    task = Task(
        task_id=0, shard=Shard(name=path, start=0, end=16), type=TASK_TRAINING
    )
    metrics = worker._run_training_task(task)
    assert np.isfinite(metrics["loss"])
    assert int(worker.state.step) == 4  # 16 records / mb 4, all via the scan
