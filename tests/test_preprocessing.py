"""Preprocessing layers: known-value transforms, adapt() streaming math,
host/device (numpy vs jit) agreement, and config round-trips — the
reference's elasticdl_preprocessing test surface (SURVEY.md §2 #15)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.preprocessing import (
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
    Normalizer,
    RoundIdentity,
    ToNumber,
)


def test_hashing_int_deterministic_and_in_range():
    layer = Hashing(100)
    x = np.array([[1, 2], [3, 2**40]])
    out = layer(x)
    assert out.shape == x.shape
    assert ((out >= 0) & (out < 100)).all()
    np.testing.assert_array_equal(out, layer(x.copy()))
    # different values spread (not all the same bucket)
    assert len(np.unique(layer(np.arange(1000)))) > 50


def test_hashing_host_device_agree():
    layer = Hashing(97)
    x = np.array([0, 1, 7, 123456789, 2**30])
    host = layer(x)
    dev = jax.jit(layer)(jnp.asarray(x))
    np.testing.assert_array_equal(host, np.asarray(dev))


def test_hashing_strings():
    layer = Hashing(50)
    out = layer(np.array(["apple", "banana", "apple"]))
    assert out[0] == out[2]
    assert ((out >= 0) & (out < 50)).all()


def test_index_lookup_adapt_frequency_order():
    layer = IndexLookup(num_oov=1)
    layer.adapt(np.array(["b", "a", "b", "c", "b", "a"]))
    assert layer.vocabulary == ["b", "a", "c"]
    out = layer(np.array(["b", "a", "c", "zzz"]))
    np.testing.assert_array_equal(out[:3], [1, 2, 3])
    assert out[3] == 0  # oov bucket
    assert layer.vocab_size == 4


def test_index_lookup_int_jit_matches_host():
    layer = IndexLookup(num_oov=2)
    layer.adapt(np.array([10, 10, 20, 30, 20, 10]))
    x = np.array([10, 20, 30, 999])
    host = layer(x)
    dev = jax.jit(layer)(jnp.asarray(x))
    np.testing.assert_array_equal(host, np.asarray(dev))


def test_index_lookup_no_oov_jit_refuses():
    layer = IndexLookup(vocabulary=[10, 20], num_oov=0)
    with pytest.raises(ValueError, match="num_oov"):
        layer(jnp.array([15]))
    # host path: explicit KeyError per OOV value
    with pytest.raises(KeyError):
        layer(np.array([15]))


def test_index_lookup_string_jit_raises():
    layer = IndexLookup()
    layer.adapt(np.array(["a", "b"]))
    with pytest.raises(TypeError):
        layer(jnp.zeros((2,), jnp.int32))


def test_normalizer_streaming_equals_full():
    rng = np.random.default_rng(1)
    data = rng.normal(5.0, 3.0, (1000, 4))
    full = Normalizer().adapt(data)
    streamed = Normalizer().adapt([data[:300], data[300:450], data[450:]])
    np.testing.assert_allclose(full.mean, streamed.mean, rtol=1e-10)
    np.testing.assert_allclose(full.variance, streamed.variance, rtol=1e-10)
    out = full(data.astype(np.float32))
    np.testing.assert_allclose(out.mean(0), 0.0, atol=1e-3)
    np.testing.assert_allclose(out.std(0), 1.0, atol=1e-2)


def test_normalizer_jit():
    layer = Normalizer(mean=[2.0], variance=[4.0])
    out = jax.jit(layer)(jnp.array([[4.0], [0.0]]))
    np.testing.assert_allclose(np.asarray(out), [[1.0], [-1.0]], atol=1e-3)


def test_discretization_quantiles_and_jit():
    data = np.arange(1000, dtype=np.float64)
    layer = Discretization(num_bins=4).adapt(data)
    assert len(layer.bin_boundaries) == 3
    x = np.array([0.0, 300.0, 600.0, 950.0])
    host = layer(x)
    np.testing.assert_array_equal(host, [0, 1, 2, 3])
    dev = jax.jit(layer)(jnp.asarray(x, jnp.float32))
    np.testing.assert_array_equal(np.asarray(dev), host)


def test_round_identity():
    layer = RoundIdentity(10)
    out = layer(np.array([0.4, 3.6, 99.0, -1.0]))
    np.testing.assert_array_equal(out, [0, 4, 9, 0])
    dev = jax.jit(layer)(jnp.array([0.4, 3.6]))
    np.testing.assert_array_equal(np.asarray(dev), [0, 4])


def test_to_number():
    layer = ToNumber(out_dtype="float32", default=-1.0)
    out = layer(np.array(["3.5", "", "junk", b"2"]))
    np.testing.assert_allclose(out, [3.5, -1.0, -1.0, 2.0])
    # numeric passthrough
    np.testing.assert_allclose(layer(np.array([1, 2])), [1.0, 2.0])


def test_concatenate_with_offset():
    layer = ConcatenateWithOffset([10, 20, 5])
    a = np.array([1, 2])
    b = np.array([[0, 3], [19, 4]])
    c = np.array([4, 0])
    out = layer([a, b, c])
    assert out.shape == (2, 4)
    np.testing.assert_array_equal(out[0], [1, 10, 13, 34])
    np.testing.assert_array_equal(out[1], [2, 29, 14, 30])
    assert layer.total_size == 35
    with pytest.raises(ValueError):
        layer([a, b])


def test_index_lookup_bytes_vocab_json_safe():
    layer = IndexLookup(num_oov=1).adapt(np.array([b"x", b"y", b"x"]))
    cfg = json.loads(json.dumps(layer.get_config()))
    rebuilt = IndexLookup.from_config(cfg)
    # bytes and str inputs resolve to the same indices, before and after
    np.testing.assert_array_equal(
        layer(np.array([b"x", "y", b"zzz"], object)),
        rebuilt(np.array(["x", b"y", "zzz"], object)),
    )


def test_config_roundtrips_are_json_safe():
    layers = [
        Hashing(10),
        IndexLookup(num_oov=1).adapt(np.array([5, 5, 7])),
        Normalizer().adapt(np.ones((4, 2))),
        Discretization(num_bins=3).adapt(np.arange(100.0)),
        RoundIdentity(7),
        ToNumber(),
        ConcatenateWithOffset([3, 4]),
    ]
    for layer in layers:
        cfg = json.loads(json.dumps(layer.get_config()))
        rebuilt = type(layer).from_config(cfg)
        assert rebuilt.get_config() == layer.get_config()
    # fitted lookup survives the round trip
    lk = layers[1]
    lk2 = IndexLookup.from_config(json.loads(json.dumps(lk.get_config())))
    np.testing.assert_array_equal(
        lk(np.array([5, 7, 99])), lk2(np.array([5, 7, 99]))
    )
