"""Master control plane: dispatcher queue/requeue semantics, rendezvous
membership versioning, eval aggregation, and the servicer both via direct
calls (no network — the reference's decisive test pattern, SURVEY.md §4) and
over a real localhost gRPC channel."""

import threading

import pytest

from elasticdl_tpu.common.rpc import JsonRpcClient
from elasticdl_tpu.data.reader import Shard
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
from elasticdl_tpu.master.task_dispatcher import (
    TASK_EVALUATION,
    Task,
    TaskDispatcher,
)


def _shards(n, size=10):
    return [Shard("f", i * size, (i + 1) * size) for i in range(n)]


class TestTaskDispatcher:
    def test_handout_and_done(self):
        d = TaskDispatcher(_shards(3))
        tasks = [d.get_task("w0") for _ in range(3)]
        assert all(t is not None for t in tasks)
        assert d.get_task("w0") is None and not d.finished()
        for t in tasks:
            assert d.report(t.task_id, True)
        assert d.finished()
        assert d.counts()["done"] == 3

    def test_failure_requeues(self):
        d = TaskDispatcher(_shards(1))
        t = d.get_task("w0")
        d.report(t.task_id, False)
        t2 = d.get_task("w1")
        assert t2.shard == t.shard
        d.report(t2.task_id, True)
        assert d.finished()

    def test_dead_worker_recovery(self):
        d = TaskDispatcher(_shards(4))
        got_w0 = [d.get_task("w0"), d.get_task("w0")]
        d.get_task("w1")
        lost = d.recover_tasks("w0")
        assert {t.task_id for t in lost} == {t.task_id for t in got_w0}
        # The lost shards are re-dispatchable; a late report from the dead
        # worker is rejected as stale.
        assert not d.report(got_w0[0].task_id, True)
        remaining = []
        while (t := d.get_task("w2")) is not None:
            remaining.append(t)
        assert len(remaining) == 3  # 2 recovered + 1 never handed out

    def test_epochs_refill(self):
        d = TaskDispatcher(_shards(2), num_epochs=3)
        seen = 0
        while not d.finished():
            t = d.get_task("w0")
            if t is None:
                break
            d.report(t.task_id, True)
            seen += 1
        assert seen == 6
        assert d.counts()["epoch"] == 2

    def test_timeout_requeue(self):
        now = [0.0]
        d = TaskDispatcher(_shards(1), task_timeout_s=5.0, clock=lambda: now[0])
        t = d.get_task("w0")
        now[0] = 10.0
        t2 = d.get_task("w1")
        assert t2 is not None and t2.shard == t.shard
        # Task ids are stable across requeues (at-least-once): the slow
        # worker's late success still completes the task...
        assert d.report(t.task_id, True)
        assert d.finished()
        # ...and the re-handed copy's report is then stale.
        assert not d.report(t2.task_id, True)

    def test_poison_task_abandoned_after_max_retries(self):
        d = TaskDispatcher(_shards(1), max_task_retries=2)
        for _ in range(3):  # initial attempt + 2 retries
            t = d.get_task("w0")
            assert t is not None
            d.report(t.task_id, False)
        assert d.get_task("w0") is None
        assert d.finished()
        assert d.counts()["abandoned"] == 1

    def test_task_serialization(self):
        t = Task(7, Shard("file.rio", 10, 20), TASK_EVALUATION, epoch=1)
        assert Task.from_dict(t.to_dict()) == t


class TestRendezvous:
    def test_versioned_membership(self):
        r = RendezvousServer()
        v1 = r.register("w0")
        v2 = r.register("w1")
        assert v2 == v1 + 1
        assert r.register("w0") == v2  # idempotent re-register
        m = r.membership()
        assert m["workers"] == ["w0", "w1"]
        assert m["ranks"] == {"w0": 0, "w1": 1}
        v3 = r.remove("w0")
        assert v3 == v2 + 1

    def test_heartbeat_reaping(self):
        now = [0.0]
        r = RendezvousServer(heartbeat_timeout_s=10.0, clock=lambda: now[0])
        r.register("w0")
        r.register("w1")
        now[0] = 8.0
        r.heartbeat("w1")
        now[0] = 15.0
        assert r.reap_dead() == ["w0"]
        assert r.membership()["workers"] == ["w1"]

    def test_listener_fires(self):
        r = RendezvousServer()
        events = []
        r.add_listener(lambda v, m: events.append((v, list(m))))
        r.register("w0")
        r.remove("w0")
        assert events == [(1, ["w0"]), (2, [])]


class TestEvaluationService:
    def test_interval_trigger_and_aggregation(self):
        ev = EvaluationService(_shards(2), evaluation_steps=100)
        assert not ev.maybe_trigger(50)
        assert ev.maybe_trigger(100)
        assert not ev.maybe_trigger(150)  # round in flight
        for _ in range(2):
            t = ev.get_task("w0")
            assert t.type == TASK_EVALUATION
            ev.report_metrics({"accuracy": 0.5}, weight=10)
            ev.report_task(t.task_id, True)
        assert ev.completed_rounds() == 1
        assert ev.latest_metrics()["accuracy"] == pytest.approx(0.5)
        assert ev.maybe_trigger(250)

    def test_weighted_aggregation(self):
        ev = EvaluationService(_shards(2), evaluation_steps=1)
        ev.trigger(1)
        t1, t2 = ev.get_task("w0"), ev.get_task("w1")
        ev.report_metrics({"acc": 1.0}, weight=30)
        ev.report_task(t1.task_id, True)
        ev.report_metrics({"acc": 0.0}, weight=10)
        ev.report_task(t2.task_id, True)
        assert ev.latest_metrics()["acc"] == pytest.approx(0.75)


class TestServicer:
    def _servicer(self, n_shards=4, eval_shards=0, evaluation_steps=0):
        ev = (
            EvaluationService(_shards(eval_shards), evaluation_steps)
            if eval_shards
            else None
        )
        return MasterServicer(TaskDispatcher(_shards(n_shards)), evaluation=ev)

    def test_direct_task_loop(self):
        s = self._servicer(2)
        s.RegisterWorker({"worker_id": "w0"})
        done = 0
        while True:
            resp = s.GetTask({"worker_id": "w0"})
            if resp["task"] is None:
                assert resp["finished"]
                break
            s.ReportTaskResult(
                {"worker_id": "w0", "task_id": resp["task"]["task_id"],
                 "success": True, "model_version": done + 1}
            )
            done += 1
        assert done == 2
        assert s.JobStatus({})["model_version"] == 2

    def test_membership_change_requeues_tasks(self):
        s = self._servicer(4)
        s.RegisterWorker({"worker_id": "w0"})
        s.RegisterWorker({"worker_id": "w1"})
        s.GetTask({"worker_id": "w0"})
        s.GetTask({"worker_id": "w1"})
        s.rendezvous.remove("w0")  # pod death observed
        status = s.JobStatus({})
        assert status["todo"] == 3 and status["doing"] == 1

    def test_eval_interleaving(self):
        s = self._servicer(2, eval_shards=1, evaluation_steps=1)
        s.ReportVersion({"worker_id": "w0", "model_version": 5})
        resp = s.GetTask({"worker_id": "w0"})
        assert resp["task"]["type"] == TASK_EVALUATION
        s.ReportTaskResult(
            {"worker_id": "w0", "task_id": resp["task"]["task_id"],
             "success": True, "task_type": TASK_EVALUATION,
             "metrics": {"accuracy": 0.9}, "weight": 10}
        )
        assert s.JobStatus({})["eval_metrics"]["accuracy"] == pytest.approx(0.9)
        # Next task is a training one again.
        assert s.GetTask({"worker_id": "w0"})["task"]["type"] == "training"

    def test_checkpoint_tracking(self):
        s = self._servicer()
        s.ReportCheckpoint({"path": "/ckpt/10", "step": 10})
        s.ReportCheckpoint({"path": "/ckpt/5", "step": 5})  # stale, ignored
        assert s.GetCheckpoint({}) == {"path": "/ckpt/10", "step": 10}


class TestGrpcTransport:
    def test_full_loop_over_localhost(self):
        servicer = MasterServicer(TaskDispatcher(_shards(8)))
        server = MasterServer(servicer, port=0).start()
        try:
            client = JsonRpcClient(server.address)
            client.wait_ready(10)
            membership = client.call("RegisterWorker", {"worker_id": "w0"})
            assert membership["world_size"] == 1

            def run_worker(worker_id, out):
                c = JsonRpcClient(server.address)
                c.call("RegisterWorker", {"worker_id": worker_id})
                while True:
                    resp = c.call("GetTask", {"worker_id": worker_id})
                    if resp["task"] is None:
                        break
                    c.call(
                        "ReportTaskResult",
                        {"worker_id": worker_id,
                         "task_id": resp["task"]["task_id"], "success": True},
                    )
                    out.append(resp["task"]["task_id"])
                c.close()

            done: list = []
            threads = [
                threading.Thread(target=run_worker, args=(f"w{i}", done))
                for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert len(done) == 8 and len(set(done)) == 8
            assert servicer.dispatcher.finished()
            client.close()
        finally:
            server.stop()
