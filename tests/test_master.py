"""Master control plane: dispatcher queue/requeue semantics, rendezvous
membership versioning, eval aggregation, and the servicer both via direct
calls (no network — the reference's decisive test pattern, SURVEY.md §4) and
over a real localhost gRPC channel."""

import threading

import pytest

from elasticdl_tpu.common.rpc import JsonRpcClient
from elasticdl_tpu.data.reader import Shard
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
from elasticdl_tpu.master.task_dispatcher import (
    TASK_EVALUATION,
    Task,
    TaskDispatcher,
)


def _shards(n, size=10):
    return [Shard("f", i * size, (i + 1) * size) for i in range(n)]


class TestTaskDispatcher:
    def test_handout_and_done(self):
        d = TaskDispatcher(_shards(3))
        tasks = [d.get_task("w0") for _ in range(3)]
        assert all(t is not None for t in tasks)
        assert d.get_task("w0") is None and not d.finished()
        for t in tasks:
            assert d.report(t.task_id, True)
        assert d.finished()
        assert d.counts()["done"] == 3

    def test_failure_requeues(self):
        d = TaskDispatcher(_shards(1))
        t = d.get_task("w0")
        d.report(t.task_id, False)
        t2 = d.get_task("w1")
        assert t2.shard == t.shard
        d.report(t2.task_id, True)
        assert d.finished()

    def test_dead_worker_recovery(self):
        d = TaskDispatcher(_shards(4))
        got_w0 = [d.get_task("w0"), d.get_task("w0")]
        d.get_task("w1")
        lost = d.recover_tasks("w0")
        assert {t.task_id for t in lost} == {t.task_id for t in got_w0}
        # The lost shards are re-dispatchable; a late report from the dead
        # worker is rejected as stale.
        assert not d.report(got_w0[0].task_id, True)
        remaining = []
        while (t := d.get_task("w2")) is not None:
            remaining.append(t)
        assert len(remaining) == 3  # 2 recovered + 1 never handed out

    def test_epochs_refill(self):
        d = TaskDispatcher(_shards(2), num_epochs=3)
        seen = 0
        while not d.finished():
            t = d.get_task("w0")
            if t is None:
                break
            d.report(t.task_id, True)
            seen += 1
        assert seen == 6
        assert d.counts()["epoch"] == 2

    def test_timeout_requeue(self):
        now = [0.0]
        d = TaskDispatcher(_shards(1), task_timeout_s=5.0, clock=lambda: now[0])
        t = d.get_task("w0")
        now[0] = 10.0
        t2 = d.get_task("w1")
        assert t2 is not None and t2.shard == t.shard
        # Task ids are stable across requeues (at-least-once): the slow
        # worker's late success still completes the task...
        assert d.report(t.task_id, True)
        assert d.finished()
        # ...and the re-handed copy's report is then stale.
        assert not d.report(t2.task_id, True)

    def test_poison_task_abandoned_after_max_retries(self):
        d = TaskDispatcher(_shards(1), max_task_retries=2)
        for _ in range(3):  # initial attempt + 2 retries
            t = d.get_task("w0")
            assert t is not None
            d.report(t.task_id, False)
        assert d.get_task("w0") is None
        assert d.finished()
        assert d.counts()["abandoned"] == 1

    def test_task_serialization(self):
        t = Task(7, Shard("file.rio", 10, 20), TASK_EVALUATION, epoch=1)
        assert Task.from_dict(t.to_dict()) == t


class TestRendezvous:
    def test_versioned_membership(self):
        r = RendezvousServer()
        v1 = r.register("w0")
        v2 = r.register("w1")
        assert v2 == v1 + 1
        assert r.register("w0") == v2  # idempotent re-register
        m = r.membership()
        assert m["workers"] == ["w0", "w1"]
        assert m["ranks"] == {"w0": 0, "w1": 1}
        v3 = r.remove("w0")
        assert v3 == v2 + 1

    def test_heartbeat_reaping(self):
        now = [0.0]
        r = RendezvousServer(heartbeat_timeout_s=10.0, clock=lambda: now[0])
        r.register("w0")
        r.register("w1")
        now[0] = 8.0
        r.heartbeat("w1")
        now[0] = 15.0
        assert r.reap_dead() == ["w0"]
        assert r.membership()["workers"] == ["w1"]

    def test_listener_fires(self):
        r = RendezvousServer()
        events = []
        r.add_listener(lambda v, m: events.append((v, list(m))))
        r.register("w0")
        r.remove("w0")
        assert events == [(1, ["w0"]), (2, [])]


class TestEvaluationService:
    def test_interval_trigger_and_aggregation(self):
        ev = EvaluationService(_shards(2), evaluation_steps=100)
        assert not ev.maybe_trigger(50)
        assert ev.maybe_trigger(100)
        assert not ev.maybe_trigger(150)  # round in flight
        for _ in range(2):
            t = ev.get_task("w0")
            assert t.type == TASK_EVALUATION
            ev.report_metrics({"accuracy": 0.5}, weight=10)
            ev.report_task(t.task_id, True)
        assert ev.completed_rounds() == 1
        assert ev.latest_metrics()["accuracy"] == pytest.approx(0.5)
        assert ev.maybe_trigger(250)

    def test_weighted_aggregation(self):
        ev = EvaluationService(_shards(2), evaluation_steps=1)
        ev.trigger(1)
        t1, t2 = ev.get_task("w0"), ev.get_task("w1")
        ev.report_metrics({"acc": 1.0}, weight=30)
        ev.report_task(t1.task_id, True)
        ev.report_metrics({"acc": 0.0}, weight=10)
        ev.report_task(t2.task_id, True)
        assert ev.latest_metrics()["acc"] == pytest.approx(0.75)


class TestServicer:
    def _servicer(self, n_shards=4, eval_shards=0, evaluation_steps=0):
        ev = (
            EvaluationService(_shards(eval_shards), evaluation_steps)
            if eval_shards
            else None
        )
        return MasterServicer(TaskDispatcher(_shards(n_shards)), evaluation=ev)

    def test_direct_task_loop(self):
        s = self._servicer(2)
        s.RegisterWorker({"worker_id": "w0"})
        done = 0
        while True:
            resp = s.GetTask({"worker_id": "w0"})
            if resp["task"] is None:
                assert resp["finished"]
                break
            s.ReportTaskResult(
                {"worker_id": "w0", "task_id": resp["task"]["task_id"],
                 "success": True, "model_version": done + 1}
            )
            done += 1
        assert done == 2
        assert s.JobStatus({})["model_version"] == 2

    def test_membership_change_requeues_tasks(self):
        s = self._servicer(4)
        s.RegisterWorker({"worker_id": "w0"})
        s.RegisterWorker({"worker_id": "w1"})
        s.GetTask({"worker_id": "w0"})
        s.GetTask({"worker_id": "w1"})
        s.rendezvous.remove("w0")  # pod death observed
        status = s.JobStatus({})
        assert status["todo"] == 3 and status["doing"] == 1

    def test_eval_interleaving(self):
        s = self._servicer(2, eval_shards=1, evaluation_steps=1)
        s.ReportVersion({"worker_id": "w0", "model_version": 5})
        resp = s.GetTask({"worker_id": "w0"})
        assert resp["task"]["type"] == TASK_EVALUATION
        s.ReportTaskResult(
            {"worker_id": "w0", "task_id": resp["task"]["task_id"],
             "success": True, "task_type": TASK_EVALUATION,
             "metrics": {"accuracy": 0.9}, "weight": 10}
        )
        assert s.JobStatus({})["eval_metrics"]["accuracy"] == pytest.approx(0.9)
        # Next task is a training one again.
        assert s.GetTask({"worker_id": "w0"})["task"]["type"] == "training"

    def test_checkpoint_tracking(self):
        s = self._servicer()
        s.ReportCheckpoint({"path": "/ckpt/10", "step": 10})
        s.ReportCheckpoint({"path": "/ckpt/5", "step": 5})  # stale, ignored
        assert s.GetCheckpoint({}) == {"path": "/ckpt/10", "step": 10}


class TestGrpcTransport:
    def test_full_loop_over_localhost(self):
        servicer = MasterServicer(TaskDispatcher(_shards(8)))
        server = MasterServer(servicer, port=0).start()
        try:
            client = JsonRpcClient(server.address)
            client.wait_ready(10)
            membership = client.call("RegisterWorker", {"worker_id": "w0"})
            assert membership["world_size"] == 1

            def run_worker(worker_id, out):
                c = JsonRpcClient(server.address)
                c.call("RegisterWorker", {"worker_id": worker_id})
                while True:
                    resp = c.call("GetTask", {"worker_id": worker_id})
                    if resp["task"] is None:
                        break
                    c.call(
                        "ReportTaskResult",
                        {"worker_id": worker_id,
                         "task_id": resp["task"]["task_id"], "success": True},
                    )
                    out.append(resp["task"]["task_id"])
                c.close()

            done: list = []
            threads = [
                threading.Thread(target=run_worker, args=(f"w{i}", done))
                for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert len(done) == 8 and len(set(done)) == 8
            assert servicer.dispatcher.finished()
            client.close()
        finally:
            server.stop()


# ---------------- batched task leases (r9) ----------------


class TestBatchedLeases:
    def test_dispatcher_get_tasks_leases_in_order(self):
        d = TaskDispatcher(_shards(5))
        tasks = d.get_tasks("w0", 3)
        assert [t.task_id for t in tasks] == [0, 1, 2]
        assert d.counts()["doing"] == 3 and d.counts()["todo"] == 2
        # a second lease continues where the first stopped, clamped to the
        # queue
        more = d.get_tasks("w1", 10)
        assert [t.task_id for t in more] == [3, 4]
        assert d.counts()["todo"] == 0 and d.counts()["doing"] == 5
        assert d.get_tasks("w0", 4) == []

    def test_gettask_lease_response_shape(self):
        servicer = MasterServicer(TaskDispatcher(_shards(4)))
        resp = servicer.GetTask({"worker_id": "w0", "lease": 3})
        assert len(resp["tasks"]) == 3
        assert resp["task"] == resp["tasks"][0]  # pre-lease consumers
        assert not resp["finished"]
        # absent lease field = the old one-task wire shape (plus the batch
        # list of length 1)
        resp2 = servicer.GetTask({"worker_id": "w0"})
        assert len(resp2["tasks"]) == 1

    def test_lease_invalidation_on_worker_loss_requeues_exactly_once(self):
        """Every leased-but-unfinished task of a lost worker re-queues
        exactly once: the lease entered `doing` at hand-out, so the same
        recover path that covers in-flight work covers the buffer — and a
        second recovery (double eviction event) requeues nothing."""
        rendezvous = RendezvousServer()
        d = TaskDispatcher(_shards(4))
        servicer = MasterServicer(d, rendezvous=rendezvous)
        servicer.RegisterWorker({"worker_id": "w0"})
        resp = servicer.GetTask({"worker_id": "w0", "lease": 4})
        leased_ids = [t["task_id"] for t in resp["tasks"]]
        assert len(leased_ids) == 4 and d.counts()["doing"] == 4
        # w0 finishes one leased task, then dies holding the other three.
        servicer.ReportTaskResult(
            {"worker_id": "w0", "task_id": leased_ids[0], "success": True}
        )
        servicer.DeregisterWorker({"worker_id": "w0"})
        c = d.counts()
        assert c["doing"] == 0 and c["todo"] == 3 and c["done"] == 1
        # exactly once: a straggling second recovery finds nothing
        assert d.recover_tasks("w0") == []
        assert d.counts()["todo"] == 3
        # the requeued leases complete under a replacement worker
        servicer.RegisterWorker({"worker_id": "w1"})
        resp = servicer.GetTask({"worker_id": "w1", "lease": 8})
        assert sorted(t["task_id"] for t in resp["tasks"]) == sorted(
            leased_ids[1:]
        )
        for t in resp["tasks"]:
            servicer.ReportTaskResult(
                {"worker_id": "w1", "task_id": t["task_id"], "success": True}
            )
        assert d.finished() and d.counts()["done"] == 4

    def test_group_task_lease_walks_log_consistently(self):
        """GetGroupTask lease batching is shared-log read-ahead: whichever
        member asks first materializes the entries; every member sees the
        identical sequence, and the batch ends at the job-end marker."""
        rendezvous = RendezvousServer()
        servicer = MasterServicer(
            TaskDispatcher(_shards(3)), rendezvous=rendezvous
        )
        v = rendezvous.register("w0")
        v = rendezvous.register("w1")
        rendezvous.heartbeat("w0", v)
        rendezvous.heartbeat("w1", v)
        r0 = servicer.GetGroupTask(
            {"worker_id": "w0", "seq": 0, "version": v, "lease": 2}
        )
        assert not r0["stale"]
        assert [e["task"]["task_id"] for e in r0["entries"]] == [0, 1]
        assert r0["task"] == r0["entries"][0]["task"]
        # the peer replays the SAME entries from the log
        r1 = servicer.GetGroupTask(
            {"worker_id": "w1", "seq": 0, "version": v, "lease": 2}
        )
        assert [e["task"]["task_id"] for e in r1["entries"]] == [0, 1]
        # next batch: one real task left, then tasks drain; the lease stops
        # rather than logging a transient none
        for tid in (0, 1):
            servicer.ReportTaskResult(
                {"worker_id": "w0", "task_id": tid, "success": True}
            )
        r2 = servicer.GetGroupTask(
            {"worker_id": "w0", "seq": 2, "version": v, "lease": 4}
        )
        ids = [e["task"]["task_id"] for e in r2["entries"] if e["task"]]
        assert ids == [2]
        servicer.ReportTaskResult(
            {"worker_id": "w0", "task_id": 2, "success": True}
        )
        # the finished marker is logged and closes the batch
        r3 = servicer.GetGroupTask(
            {"worker_id": "w1", "seq": 3, "version": v, "lease": 4}
        )
        assert r3["entries"][-1]["finished"] and r3["entries"][-1]["task"] is None
        # a version bump invalidates the log and requeues nothing twice
        v2 = rendezvous.register("w2")
        stale = servicer.GetGroupTask(
            {"worker_id": "w0", "seq": 3, "version": v, "lease": 2}
        )
        assert stale["stale"]

    def test_requeue_flag_does_not_charge_retry_budget(self):
        """A lease/prep abandon (success=False, requeue=True) requeues
        without counting as a failure: a task bounced by many elastic
        events must never be poison-abandoned."""
        d = TaskDispatcher(_shards(1), max_task_retries=2)
        servicer = MasterServicer(d)
        for _ in range(6):  # far past max_task_retries
            t = servicer.GetTask({"worker_id": "w0"})["task"]
            servicer.ReportTaskResult({
                "worker_id": "w0", "task_id": t["task_id"],
                "success": False, "requeue": True,
            })
        c = d.counts()
        assert c["todo"] == 1 and c["abandoned"] == 0
        # ...while real failures still burn the budget and poison out
        for _ in range(3):
            t = servicer.GetTask({"worker_id": "w0"})["task"]
            servicer.ReportTaskResult({
                "worker_id": "w0", "task_id": t["task_id"], "success": False,
            })
        assert d.counts()["abandoned"] == 1

    def test_heartbeat_eval_pending_hint(self):
        """The heartbeat carries eval_pending while an eval round has
        undispatched tasks — the lease-return trigger that keeps eval
        preemption prompt under batched leases."""
        rendezvous = RendezvousServer()
        evaluation = EvaluationService(_shards(2), evaluation_steps=5)
        servicer = MasterServicer(
            TaskDispatcher(_shards(2)), rendezvous=rendezvous,
            evaluation=evaluation,
        )
        servicer.RegisterWorker({"worker_id": "w0"})
        assert "eval_pending" not in servicer.Heartbeat({"worker_id": "w0"})
        assert evaluation.trigger(1)
        assert servicer.Heartbeat({"worker_id": "w0"})["eval_pending"] is True
        # both eval tasks handed out -> nothing left to pull -> hint gone
        e0 = servicer.GetTask({"worker_id": "w0"})["task"]
        e1 = servicer.GetTask({"worker_id": "w0"})["task"]
        assert {e0["type"], e1["type"]} == {TASK_EVALUATION}
        assert "eval_pending" not in servicer.Heartbeat({"worker_id": "w0"})

    def test_heartbeat_draining_hint_bounds_max_steps_overshoot(self):
        """After --max_steps the heartbeat carries `draining`; returned
        buffered leases are dropped by the stopped dispatcher (never
        retrained), restoring the pre-lease overshoot bound."""
        d = TaskDispatcher(_shards(4))
        servicer = MasterServicer(
            d, rendezvous=RendezvousServer(), max_steps=8
        )
        servicer.RegisterWorker({"worker_id": "w0"})
        resp = servicer.GetTask({"worker_id": "w0", "lease": 4})
        assert len(resp["tasks"]) == 4
        assert "draining" not in servicer.Heartbeat({"worker_id": "w0"})
        servicer.ReportTaskResult({
            "worker_id": "w0", "task_id": resp["tasks"][0]["task_id"],
            "success": True, "model_version": 8,
        })
        assert servicer.Heartbeat({"worker_id": "w0"})["draining"] is True
        # the worker returns its buffer; the stopped dispatcher drops it
        for t in resp["tasks"][1:]:
            servicer.ReportTaskResult({
                "worker_id": "w0", "task_id": t["task_id"],
                "success": False, "requeue": True,
            })
        c = d.counts()
        assert c["todo"] == 0 and c["doing"] == 0
        assert c["done"] == 1 and c["abandoned"] == 3
        assert d.finished()

    def test_group_lease_read_ahead_clamps_under_eval_pressure(self):
        """The lockstep log must not speculatively materialize training
        entries past a pending eval round (or a max-steps drain): every
        logged entry commits the whole gang.  Under pressure the batch
        falls back to one new entry per call."""
        rendezvous = RendezvousServer()
        evaluation = EvaluationService(_shards(2), evaluation_steps=5)
        servicer = MasterServicer(
            TaskDispatcher(_shards(3)), rendezvous=rendezvous,
            evaluation=evaluation,
        )
        v = rendezvous.register("w0")
        rendezvous.heartbeat("w0", v)
        assert evaluation.trigger(1)
        r = servicer.GetGroupTask(
            {"worker_id": "w0", "seq": 0, "version": v, "lease": 4}
        )
        # one eval entry materialized; the second eval task still pends,
        # so NO training read-ahead happened behind it
        assert len(r["entries"]) == 1
        assert r["entries"][0]["task"]["type"] == TASK_EVALUATION
        # pressure cleared (both eval tasks handed out): batching resumes
        r2 = servicer.GetGroupTask(
            {"worker_id": "w0", "seq": 1, "version": v, "lease": 4}
        )
        assert len(r2["entries"]) > 1
        assert r2["entries"][0]["task"]["type"] == TASK_EVALUATION
        assert r2["entries"][1]["task"]["type"] != TASK_EVALUATION
