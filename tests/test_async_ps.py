"""Async parameter-server mode (--use_async; SURVEY §2 #9 "async or
sync-by-version"): host-tier row pulls for batch n+1 overlap the in-flight
device step, reading rows one un-applied push stale."""

import numpy as np
import pytest

from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.parallel.trainer import Trainer


def _native_available() -> bool:
    from elasticdl_tpu.ps.host_store import native_lib_available

    return native_lib_available()


needs_native = pytest.mark.skipif(
    not _native_available(), reason="native lib unavailable"
)


def _spec():
    return load_model_spec(
        "elasticdl_tpu.models", "deepfm.model_spec",
        buckets_per_feature=64, embedding_dim=8, hidden=(16,),
        host_tier=True, compute_dtype="float32",
    )


def _batches(n_batches, seed0=0, b=16):
    out = []
    for s in range(n_batches):
        rng = np.random.RandomState(seed0 + s)
        out.append({
            "dense": rng.rand(b, 13).astype(np.float32) * 100,
            "cat": rng.randint(0, 1 << 20, (b, 26)).astype(np.int64),
            "labels": rng.randint(0, 2, (b,)).astype(np.int32),
        })
    return out


def _run(devices, use_async, n_batches, async_staleness=1):
    """Depth pinned to 1 (not the config default, which is data-chosen and
    may move — artifacts/async_depth_r05.json): these tests characterize
    the CLASSIC async window and its sync equivalence."""
    import jax

    spec = _spec()
    trainer = Trainer(
        spec,
        JobConfig(
            distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
            async_staleness=async_staleness,
        ),
        create_mesh(devices[:4]),
    )
    state = trainer.init_state(jax.random.key(0))
    state, metrics = trainer.run_train_steps(
        state, _batches(n_batches), use_async=use_async
    )
    key = list(spec.host_io)[0]
    probe = np.arange(64, dtype=np.int64)
    return [float(m["loss"]) for m in metrics], trainer._host_stores[key].pull(probe)


@needs_native
def test_single_batch_async_equals_sync(devices):
    """With one batch there is nothing to overlap: the pipeline degenerates
    to pull->step->push and must match sync bit-for-bit (losses AND rows)."""
    sync_losses, sync_rows = _run(devices, use_async=False, n_batches=1)
    async_losses, async_rows = _run(devices, use_async=True, n_batches=1)
    assert async_losses == sync_losses
    np.testing.assert_array_equal(async_rows, sync_rows)


@needs_native
def test_async_staleness_bounded_by_one(devices):
    """Multi-batch: batch 0's loss is identical (same fresh rows); later
    batches may see 1-push-stale rows, but every push still lands and
    training still converges."""
    sync_losses, sync_rows = _run(devices, use_async=False, n_batches=4)
    async_losses, async_rows = _run(devices, use_async=True, n_batches=4)
    assert async_losses[0] == sync_losses[0]
    assert all(np.isfinite(async_losses))
    assert async_losses[-1] < async_losses[0]
    # Every push landed: rows this run touched moved off the sync run's
    # values by at most a staleness-induced delta, never back to init —
    # compare against a NEVER-trained store's deterministic init rows.
    _, init_rows = _run(devices, use_async=True, n_batches=0)
    trained_mask = np.any(sync_rows != init_rows, axis=-1)
    assert trained_mask.any()
    # Async trained the same touched rows (all 4 batches' pushes applied).
    async_moved = np.any(async_rows != init_rows, axis=-1)
    np.testing.assert_array_equal(async_moved, trained_mask)


@needs_native
def test_worker_task_uses_async_driver(devices, monkeypatch):
    """--use_async reaches the trainer through the worker's training-task
    loop, and metrics aggregate across the task's minibatches either way."""
    import jax

    from elasticdl_tpu.data.reader import Shard, create_data_reader
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.task_dispatcher import TASK_TRAINING, Task
    from elasticdl_tpu.worker.worker import Worker

    import tempfile, os

    tmp = tempfile.mkdtemp()
    path = os.path.join(tmp, "criteo.rio")
    generate("criteo", path, 48)
    spec = _spec()
    config = JobConfig(
        model_def="deepfm.model_spec",
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        training_data=path,
        minibatch_size=16,
        use_async=True,
    )
    reader = create_data_reader(path)
    worker = Worker(
        config, master=None, reader=reader, spec=spec, devices=jax.devices()[:4]
    )
    worker._apply_membership(
        {"version": 0, "world_size": 1, "ranks": {"w": 0}}, initial=True
    )
    worker.state = worker.trainer.init_state(jax.random.key(0))

    seen = {}
    orig = Trainer.run_train_steps

    def spy(self, state, batches, use_async=False, pre_sharded=False):
        seen["use_async"] = use_async
        return orig(
            self, state, batches, use_async=use_async, pre_sharded=pre_sharded
        )

    monkeypatch.setattr(Trainer, "run_train_steps", spy)
    task = Task(task_id=0, shard=Shard(name=path, start=0, end=48), type=TASK_TRAINING)
    metrics = worker._run_training_task(task)
    assert seen["use_async"] is True
    assert np.isfinite(metrics["loss"])


@needs_native
def test_async_depth_parameter(devices):
    """--async_staleness D: pulls may see up to D un-applied pushes, but
    every push still lands by the end of the run; depth 1 reproduces the
    r3 behavior exactly."""
    import jax

    spec = _spec()
    for depth in (1, 2, 4, 8):  # 8 > n_batches: everything drains at end
        trainer = Trainer(
            spec,
            JobConfig(
                distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
                async_staleness=depth,
            ),
            create_mesh(devices[:4]),
        )
        pushes = []
        orig = trainer._push_host_grads
        trainer._push_host_grads = lambda *a: (pushes.append(1), orig(*a))[1]
        state = trainer.init_state(jax.random.key(0))
        state, metrics = trainer.run_train_steps(
            state, _batches(5), use_async=True
        )
        assert len(pushes) == 5, f"depth {depth}: every push must land"
        assert all(np.isfinite(float(m["loss"])) for m in metrics)

    # depth 1 == the old pipeline bit-for-bit
    l1, r1 = _run(devices, use_async=True, n_batches=4)
    trainer = Trainer(
        spec,
        JobConfig(
            distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
            async_staleness=1,
        ),
        create_mesh(devices[:4]),
    )
    state = trainer.init_state(jax.random.key(0))
    state, metrics = trainer.run_train_steps(
        state, _batches(4), use_async=True
    )
    key = list(spec.host_io)[0]
    probe = np.arange(64, dtype=np.int64)
    np.testing.assert_array_equal(
        trainer._host_stores[key].pull(probe), r1
    )
    assert [float(m["loss"]) for m in metrics] == l1
