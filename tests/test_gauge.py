"""graftgauge (r14): registry semantics, Prometheus exposition, fleet
aggregation, heartbeat envelope compat, live endpoints, and the
bench_regress trajectory gate.

The concurrency tests assert EXACT totals — the registry's counters back
the goodput computer, and an approximate examples-trained count would
make a live goodput ratio lie.  The bucket tests pin the live histogram
grid to ``tools/artifact.latency_stats``'s: one grid, so a scrape and a
stamped artifact bucket the same sample identically.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

import pytest

from elasticdl_tpu.common import gauge
from elasticdl_tpu.common.metrics_http import MetricsHTTPServer


# ---------------------------------------------------------------------------
# registry primitives
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_exact_under_threads(self):
        reg = gauge.Registry()
        c = reg.counter("edl_t_total", "t")
        n_threads, per = 8, 5000

        def work():
            for _ in range(per):
                c.inc()

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == n_threads * per

    def test_histogram_exact_under_threads(self):
        reg = gauge.Registry()
        h = reg.histogram("edl_t_ms", "t")
        n_threads, per = 6, 3000

        def work(i):
            for k in range(per):
                h.observe(float(i * per + k) % 97)

        threads = [
            threading.Thread(target=work, args=(i,))
            for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = h.snapshot()
        assert snap["count"] == n_threads * per
        assert sum(snap["counts"]) == n_threads * per

    def test_bucket_semantics_match_latency_stats(self):
        # The exact-edge cases are the ones that drift: bisect_left vs
        # searchsorted(side="left") must agree that a sample AT an edge
        # lands in the (prev, edge] bin.
        from tools.artifact import latency_stats

        samples = [0.05, 0.1, 0.11, 1.0, 2.0, 2.0001, 9999.0, 10000.0,
                   10000.1, 50000.0]
        h = gauge.Histogram()
        for s in samples:
            h.observe(s)
        stats = latency_stats(samples, buckets=True)
        assert h.snapshot()["counts"] == stats["hist"]["counts"]
        assert h.snapshot()["edges"] == stats["hist"]["edges_ms"]

    def test_shared_grid_is_the_artifact_grid(self):
        import tools.artifact as artifact

        assert artifact.DEFAULT_BUCKET_EDGES_MS is gauge.DEFAULT_BUCKET_EDGES_MS

    def test_type_conflict_raises(self):
        reg = gauge.Registry()
        reg.counter("edl_x_total")
        with pytest.raises(ValueError):
            reg.gauge("edl_x_total")

    def test_get_or_create_idempotent_and_labeled_series(self):
        reg = gauge.Registry()
        a = reg.counter("edl_x_total", labels={"w": "0"})
        b = reg.counter("edl_x_total", labels={"w": "0"})
        c = reg.counter("edl_x_total", labels={"w": "1"})
        assert a is b and a is not c

    def test_disabled_registry_is_noop_and_flippable(self):
        reg = gauge.Registry(enabled=False)
        c = reg.counter("edl_x_total")
        h = reg.histogram("edl_h_ms")
        c.inc()
        h.observe(1.0)
        assert c.value() == 0 and h.snapshot()["count"] == 0
        reg.configure(enabled=True)
        c.inc()
        h.observe(1.0)
        assert c.value() == 1 and h.snapshot()["count"] == 1

    def test_quantile_interpolates_and_bounds(self):
        h = gauge.Histogram()
        assert h.quantile(0.99) is None
        for _ in range(100):
            h.observe(1.5)  # (1.0, 2.0] bucket
        q = h.quantile(0.5)
        assert 1.0 <= q <= 2.0
        h2 = gauge.Histogram()
        h2.observe(10**6)  # overflow bucket: the last edge, a lower bound
        assert h2.quantile(0.99) == h2.edges[-1]

    def test_collector_runs_at_snapshot_and_errors_are_contained(self):
        reg = gauge.Registry()
        calls = []

        def ok():
            calls.append(1)
            reg.gauge("edl_depth").set(7.0)

        def broken():
            raise RuntimeError("boom")

        reg.add_collector(ok)
        reg.add_collector(broken)
        snap = reg.snapshot()
        assert calls and snap["edl_depth"]["samples"][0]["value"] == 7.0


# ---------------------------------------------------------------------------
# Prometheus text exposition (golden)
# ---------------------------------------------------------------------------

def test_render_prometheus_golden():
    reg = gauge.Registry()
    reg.counter("edl_examples_trained_total", "examples trained").inc(42)
    reg.gauge("edl_lease_depth", "buffered leases",
              labels={"worker": "w0"}).set(3)
    h = reg.histogram("edl_req_ms", "request wall", edges=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5.0)
    h.observe(100.0)
    text = reg.render_prometheus()
    assert text == (
        "# HELP edl_examples_trained_total examples trained\n"
        "# TYPE edl_examples_trained_total counter\n"
        "edl_examples_trained_total 42\n"
        "# HELP edl_lease_depth buffered leases\n"
        "# TYPE edl_lease_depth gauge\n"
        'edl_lease_depth{worker="w0"} 3\n'
        "# HELP edl_req_ms request wall\n"
        "# TYPE edl_req_ms histogram\n"
        'edl_req_ms_bucket{le="1"} 1\n'
        'edl_req_ms_bucket{le="10"} 2\n'
        'edl_req_ms_bucket{le="+Inf"} 3\n'
        "edl_req_ms_sum 105.5\n"
        "edl_req_ms_count 3\n"
    )


def test_watch_job_parse_roundtrip():
    from tools.watch_job import parse_prometheus, render_table

    reg = gauge.Registry()
    reg.counter("edl_a_total").inc(5)
    reg.gauge("edl_b", labels={"worker": "w1"}).set(2.5)
    h = reg.histogram("edl_c_ms")
    for v in (1.5, 1.5, 300.0):
        h.observe(v)
    families = parse_prometheus(reg.render_prometheus())
    assert families["edl_a_total"]["samples"][0]["value"] == 5.0
    b = families["edl_b"]["samples"][0]
    assert b["labels"] == {"worker": "w1"} and b["value"] == 2.5
    assert families["edl_c_ms"]["type"] == "histogram"
    table = render_table(families)
    assert "edl_a_total" in table and "n=3" in table


def test_render_families_skips_malformed_remote_samples():
    # The merged fleet view renders REMOTE input: garbage shapes must be
    # skipped, never a scrape 500.
    text = gauge.render_families({
        "edl_ok": {"type": "gauge", "help": "",
                   "samples": [{"labels": {}, "value": 1.0}]},
        "edl_bad1": {"type": "gauge", "samples": [7, {"value": "x"}]},
        "edl_bad2": "not-a-dict",
        "edl_bad3": {"type": "histogram", "samples": [
            {"labels": {}, "value": {"edges": [1.0], "counts": [1]}},
        ]},  # counts != edges+1: skipped
    })
    assert "edl_ok 1" in text
    assert "edl_bad1" not in text.split("# TYPE")[0]
    assert "bucket" not in text


# ---------------------------------------------------------------------------
# fleet-view helpers
# ---------------------------------------------------------------------------

def test_merge_snapshots_labels_per_worker_and_keeps_histograms():
    r0, r1 = gauge.Registry(), gauge.Registry()
    r0.counter(gauge.EXAMPLES_TRAINED).inc(100)
    r1.counter(gauge.EXAMPLES_TRAINED).inc(50)
    r0.histogram("edl_phase_ms", labels={"phase": "dispatch"}).observe(3.0)
    merged = gauge.merge_snapshots(
        {"w0": r0.snapshot(), "w1": r1.snapshot()}
    )
    fam = merged[gauge.EXAMPLES_TRAINED]
    by_worker = {
        s["labels"]["worker"]: s["value"] for s in fam["samples"]
    }
    assert by_worker == {"w0": 100.0, "w1": 50.0}
    hist = merged["edl_phase_ms"]["samples"][0]
    assert hist["labels"] == {"phase": "dispatch", "worker": "w0"}
    text = gauge.render_families(merged)
    assert 'edl_examples_trained_total{worker="w0"} 100' in text


class TestRateWindow:
    def test_rate_over_window_and_restart_reanchor(self):
        clock = [0.0]
        rw = gauge.RateWindow(window_s=10.0, clock=lambda: clock[0])
        rw.update("w0", 0)
        clock[0] = 2.0
        rw.update("w0", 200)
        assert rw.rates() == {"w0": 100.0}
        # Counter went BACKWARDS (worker restarted): re-anchor, never a
        # negative rate.
        clock[0] = 3.0
        rw.update("w0", 10)
        assert rw.rates() == {}
        clock[0] = 4.0
        rw.update("w0", 110)
        assert rw.rates() == {"w0": 100.0}

    def test_stale_keys_drop_out(self):
        clock = [0.0]
        rw = gauge.RateWindow(window_s=5.0, clock=lambda: clock[0])
        rw.update("dead", 0)
        clock[0] = 1.0
        rw.update("dead", 100)
        clock[0] = 2.0
        rw.update("live", 0)
        clock[0] = 3.0
        rw.update("live", 10)
        clock[0] = 8.0  # "dead" silent past the window
        assert set(rw.rates()) == {"live"}
        assert rw.rate() == pytest.approx(10.0)


# ---------------------------------------------------------------------------
# heartbeat envelope: additive compat + master aggregation
# ---------------------------------------------------------------------------

def _servicer(n_shards=4):
    from elasticdl_tpu.master.rendezvous import RendezvousServer
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    dispatcher = TaskDispatcher(
        [(i * 10, (i + 1) * 10) for i in range(n_shards)], num_epochs=1
    )
    return MasterServicer(
        dispatcher, rendezvous=RendezvousServer(heartbeat_timeout_s=30.0)
    )


def test_heartbeat_gauge_envelope_additive_compat_over_grpc():
    """Both directions of the r9/r12 additive stance over REAL gRPC: an
    old client's beat (no ``gauge`` field) passes the new server's
    schema, a new client's beat with the envelope passes too, and a
    malformed envelope degrades to ignored — never a failed heartbeat."""
    from elasticdl_tpu.common.rpc import JsonRpcClient
    from elasticdl_tpu.master.servicer import MasterServer

    servicer = _servicer()
    server = MasterServer(servicer, port=0).start()
    client = JsonRpcClient(server.address)
    try:
        client.wait_ready(10.0)
        # Old client -> new server: no envelope.
        assert "version" in client.call("Heartbeat", {"worker_id": "w0"})
        # New client -> server: real envelope banks into the fleet view.
        reg = gauge.Registry()
        reg.counter(gauge.EXAMPLES_TRAINED).inc(64)
        assert "version" in client.call(
            "Heartbeat",
            {"worker_id": "w0", "gauge": {"families": reg.snapshot()}},
        )
        assert gauge.EXAMPLES_TRAINED in servicer.fleet.fleet_snapshot()
        # Malformed envelopes: the typed schema rejects a non-dict in the
        # CALLER's frame, and a dict of garbage banks nothing — neither
        # crashes the beat.
        from elasticdl_tpu.common.rpc import SchemaError

        with pytest.raises(SchemaError):
            client.call("Heartbeat", {"worker_id": "w0", "gauge": 7})
        assert "version" in client.call(
            "Heartbeat", {"worker_id": "w0", "gauge": {"families": 9}}
        )
        # New SERVER fields are equally ignorable by old clients: the
        # response schema carries nothing gauge-shaped to strip, which is
        # the compat guarantee (nothing to misread).
    finally:
        client.close()
        server.stop()


def test_master_aggregation_two_worker_fleet():
    """Two in-process 'workers' ship envelopes on beats; the master's
    rendered view carries per-worker families, the fleet rate, goodput
    and the peak denominator."""
    servicer = _servicer()
    regs = {w: gauge.Registry() for w in ("w0", "w1")}
    counters = {
        w: r.counter(gauge.EXAMPLES_TRAINED) for w, r in regs.items()
    }
    for v0, v1 in ((100, 50), (300, 150), (500, 250)):
        counters["w0"].inc(v0)
        counters["w1"].inc(v1)
        for w, r in regs.items():
            servicer.Heartbeat(
                {"worker_id": w, "gauge": {"families": r.snapshot()}}
            )
        time.sleep(0.25)
    text = servicer.fleet.render()
    assert 'edl_examples_trained_total{worker="w0"} 900' in text
    assert 'edl_examples_trained_total{worker="w1"} 450' in text
    from tools.watch_job import parse_prometheus

    fams = parse_prometheus(text)

    def value(name):
        return fams[name]["samples"][0]["value"]

    fleet_rate = value("edl_fleet_examples_per_sec")
    assert fleet_rate > 0
    assert value("edl_fleet_examples_per_sec_peak") >= fleet_rate
    assert 0 < value("edl_goodput_under_churn") <= 1.0
    # The beats themselves registered the two workers (the rendezvous
    # revival path), so the world-size gauge reads the live membership.
    assert value("edl_world_size") == 2
    health = servicer.fleet.health()
    assert health["workers_reporting"] == ["w0", "w1"]


def test_read_device_ceiling_takes_newest_rev(tmp_path):
    from elasticdl_tpu.master.fleet_metrics import read_device_ceiling

    d = str(tmp_path)
    for name, v in (
        ("bench_r05.json", 100.0),
        ("bench_r05_latest.json", 90.0),
        ("bench_r07_latest.json", 250.0),  # newest rev wins, even if lower
        ("other_r09.json", 999.0),         # wrong family: ignored
    ):
        with open(os.path.join(d, name), "w") as f:
            json.dump({"device_step_examples_per_sec_per_chip": v}, f)
    assert read_device_ceiling(d) == 250.0
    assert read_device_ceiling(os.path.join(d, "absent")) is None


def test_goodput_vs_ceiling_uses_committed_record():
    servicer = _servicer()
    # Pin the ceiling instead of reading the repo artifact: the unit is
    # the ratio arithmetic, not the file layout.
    servicer.fleet._ceiling = 1000.0
    reg = gauge.Registry()
    c = reg.counter(gauge.EXAMPLES_TRAINED)
    c.inc(0)
    servicer.Heartbeat({"worker_id": "w0", "gauge": {"families": reg.snapshot()}})
    time.sleep(0.2)
    c.inc(100)
    servicer.Heartbeat({"worker_id": "w0", "gauge": {"families": reg.snapshot()}})
    snap = servicer.fleet.registry.snapshot()
    ceiling = snap["edl_device_ceiling_examples_per_sec"]["samples"][0]["value"]
    ratio = snap["edl_goodput_vs_ceiling"]["samples"][0]["value"]
    rate = snap["edl_fleet_examples_per_sec"]["samples"][0]["value"]
    assert ceiling == 1000.0
    assert ratio == pytest.approx(rate / 1000.0)


def test_remove_collector_unhooks_and_tolerates_absent():
    reg = gauge.Registry()
    calls = []

    def fn():
        calls.append(1)

    reg.add_collector(fn)
    reg.snapshot()
    assert len(calls) == 1
    reg.remove_collector(fn)
    reg.snapshot()
    assert len(calls) == 1
    reg.remove_collector(fn)  # already gone: no-op


def test_master_render_has_one_type_block_per_family():
    """A family living on BOTH sides of the master page (its own
    registry and the worker envelopes — edl_membership_version does)
    must render under ONE HELP/TYPE block: a spec-strict Prometheus
    parser rejects the whole scrape on a duplicate TYPE line."""
    servicer = _servicer()
    reg = gauge.Registry()
    reg.gauge("edl_membership_version", "applied membership version").set(3)
    reg.counter(gauge.EXAMPLES_TRAINED).inc(10)
    servicer.Heartbeat(
        {"worker_id": "w0", "gauge": {"families": reg.snapshot()}}
    )
    text = servicer.fleet.render()
    assert text.count("# TYPE edl_membership_version ") == 1
    # Both sides' samples survive the fold: the master's unlabeled
    # series and the worker-labeled one.
    assert "\nedl_membership_version " in text
    assert 'edl_membership_version{worker="w0"} 3' in text


def test_departed_worker_envelopes_are_bounded():
    """Dead incarnations' envelopes are pruned past DEPARTED_KEEP
    (most-recently-updated kept — the r12 departed-trace-ring stance);
    live members are never pruned."""
    from elasticdl_tpu.master.fleet_metrics import FleetMetrics

    servicer = _servicer()
    servicer.rendezvous.register("w-live")
    reg = gauge.Registry()
    reg.counter(gauge.EXAMPLES_TRAINED).inc(1)
    snap = reg.snapshot()
    servicer.fleet.record_envelope("w-live", {"families": snap})
    n_dead = FleetMetrics.DEPARTED_KEEP + 5
    for i in range(n_dead):
        servicer.fleet.record_envelope(f"w-dead-{i}", {"families": snap})
    merged = servicer.fleet.fleet_snapshot()
    workers = {
        s["labels"]["worker"]
        for s in merged[gauge.EXAMPLES_TRAINED]["samples"]
    }
    assert "w-live" in workers
    departed = workers - {"w-live"}
    assert len(departed) == FleetMetrics.DEPARTED_KEEP
    # Most-recently-updated survive: the oldest five were pruned.
    assert departed == {
        f"w-dead-{i}" for i in range(5, n_dead)
    }


def test_clear_family_drops_series_but_keeps_registration():
    reg = gauge.Registry()
    reg.gauge("edl_w", labels={"worker": "w0"}).set(5)
    reg.clear_family("edl_w")
    assert reg.snapshot(collect=False)["edl_w"]["samples"] == []
    reg.clear_family("edl_absent")  # unknown family: no-op
    # Re-registering after a clear still enforces the type.
    with pytest.raises(ValueError):
        reg.counter("edl_w")


def test_stale_per_entity_series_disappear_from_the_fleet_view():
    """A dissolved gang's lag series (and by the same mechanism a dead
    worker's rate series) must vanish from /metrics, not serve their
    last value forever."""
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    clock = [100.0]
    dispatcher = TaskDispatcher([(0, 10), (10, 20)], num_epochs=1)
    servicer = MasterServicer(dispatcher, clock=lambda: clock[0])
    with servicer._group_lock:
        servicer._group_version = 1
        servicer._gang_arrivals = {"w0": (5, 99.0), "w1": (4, 90.0)}
        servicer._gang_head = (5, 99.0)
    clock[0] = 101.0
    snap = servicer.fleet.registry.snapshot()
    assert len(snap["edl_gang_arrival_lag_seconds"]["samples"]) == 2
    # The gang dissolves (job end / reform): the lag series must go too.
    with servicer._group_lock:
        servicer._group_version = None
        servicer._gang_arrivals = {}
        servicer._gang_head = (0, None)
    snap = servicer.fleet.registry.snapshot()
    assert snap["edl_gang_arrival_lag_seconds"]["samples"] == []


def test_gang_lag_snapshot_names_the_laggard():
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    clock = [100.0]
    dispatcher = TaskDispatcher([(0, 10), (10, 20)], num_epochs=1)
    servicer = MasterServicer(dispatcher, clock=lambda: clock[0])
    servicer._group_version = 1
    with servicer._group_lock:
        servicer._gang_arrivals = {"w0": (5, 99.0), "w1": (4, 90.0)}
        servicer._gang_head = (5, 99.0)
    clock[0] = 102.0
    lag = servicer.gang_lag_snapshot()
    assert lag["w0"] == 0.0  # at the head
    # w1 trails: seconds since the HEAD arrived (now - head_t) — the
    # deadline's own clock, not now - w1's previous arrival (which would
    # read 12 s of "lag" on a healthy gang).
    assert lag["w1"] == pytest.approx(3.0)


# ---------------------------------------------------------------------------
# JSONL coexistence: one naming table, torn-line tolerance
# ---------------------------------------------------------------------------

def test_jsonl_mirror_uses_the_one_naming_table():
    servicer = _servicer()
    reg = gauge.Registry()
    reg.counter(gauge.EXAMPLES_TRAINED).inc(10)
    reg.counter(gauge.STEPS_DISPATCHED).inc(2)
    reg.counter(gauge.TASKS_DONE).inc(1)
    reg.gauge(gauge.LEASE_DEPTH).set(3)
    reg.gauge(gauge.PREP_QUEUE_DEPTH).set(1)
    reg.gauge("edl_rank").set(0)  # NOT in the table: must not leak
    reg.histogram("edl_phase_ms").observe(1.0)  # histograms never mirror
    mirror = servicer.fleet.jsonl_mirror(
        "w0", {"families": reg.snapshot()}
    )
    assert set(mirror) == set(gauge.JSONL_GAUGE_FAMILIES)
    assert mirror[gauge.EXAMPLES_TRAINED] == 10.0


def test_gauge_records_stream_to_jsonl_and_tolerate_torn_tail(tmp_path):
    from elasticdl_tpu.common.metrics import MetricsWriter, read_metrics
    from elasticdl_tpu.data.reader import Shard
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    writer = MetricsWriter(str(tmp_path), tensorboard=False)
    dispatcher = TaskDispatcher([Shard("f", 0, 10)], num_epochs=1)
    servicer = MasterServicer(dispatcher, metrics_writer=writer)
    reg = gauge.Registry()
    reg.counter(gauge.EXAMPLES_TRAINED).inc(128)
    task = dispatcher.get_task("w0")
    servicer.ReportTaskResult({
        "worker_id": "w0",
        "task_id": task.task_id,
        "success": True,
        "gauge": {"families": reg.snapshot()},
    })
    writer.close()
    records = read_metrics(str(tmp_path))
    gauges = [r for r in records if r["kind"] == "gauge"]
    assert gauges and gauges[0][gauge.EXAMPLES_TRAINED] == 128.0
    assert set(gauges[0]) - {"ts", "kind", "step"} <= set(
        gauge.JSONL_GAUGE_FAMILIES
    )
    # Torn FINAL line (crash mid-append of a gauge record): dropped, the
    # earlier records still read.
    path = os.path.join(str(tmp_path), "metrics.jsonl")
    with open(path, "a") as f:
        f.write('{"ts": 1, "kind": "gauge", "edl_examples_tra')
    assert read_metrics(str(tmp_path)) == records


# ---------------------------------------------------------------------------
# scrape endpoints
# ---------------------------------------------------------------------------

def _get(address, path="/metrics", timeout=5.0):
    with urllib.request.urlopen(
        f"http://{address}{path}", timeout=timeout
    ) as r:
        return r.status, r.read().decode()


def test_metrics_http_serves_metrics_and_healthz():
    reg = gauge.Registry()
    reg.counter("edl_x_total").inc(9)
    srv = MetricsHTTPServer(
        reg.render_prometheus, health_fn=lambda: {"role": "test"}, port=0
    ).start()
    try:
        status, body = _get(srv.address)
        assert status == 200 and "edl_x_total 9" in body
        status, body = _get(srv.address, "/healthz")
        assert status == 200
        assert json.loads(body) == {"role": "test", "status": "ok"}
        with pytest.raises(urllib.error.HTTPError):
            _get(srv.address, "/nope")
    finally:
        srv.stop()


def test_maybe_start_disabled_and_bind_failure():
    from elasticdl_tpu.common.metrics_http import maybe_start

    assert maybe_start(-1, lambda: "") is None
    srv = maybe_start(0, lambda: "edl_y 1\n")
    try:
        assert srv is not None
        # A second server on the SAME fixed port fails the bind: logs and
        # returns None instead of taking the process down.
        assert maybe_start(srv.port, lambda: "") is None
    finally:
        srv.stop()


def test_endpoint_answers_while_task_loop_is_stalled(tmp_path, devices):
    """The chaos stance: a worker wedged in an injected stall must still
    answer /metrics — the scrape server runs its own daemon threads,
    never the task loop.  Scrapes are issued CONCURRENT with the stalled
    run and must all succeed."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker

    train = str(tmp_path / "train.rio")
    generate("mnist", train, 96)
    config = JobConfig(
        model_def="mnist.model_spec",
        training_data=train,
        minibatch_size=16,
        num_minibatches_per_task=2,
        chaos="stall:point=task,ms=400,count=2",
    )
    reader = create_data_reader(train)
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    servicer = MasterServicer(TaskDispatcher(reader.create_shards(32)))
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices,
    )
    srv = MetricsHTTPServer(worker.gauges.render_prometheus, port=0).start()
    scrapes = {"ok": 0, "fail": 0}
    stop = threading.Event()

    def scrape_loop():
        while not stop.is_set():
            try:
                status, body = _get(srv.address, timeout=2.0)
                if status == 200 and "edl_" in body:
                    scrapes["ok"] += 1
                else:
                    scrapes["fail"] += 1
            except Exception:
                scrapes["fail"] += 1
            stop.wait(0.05)

    scraper = threading.Thread(target=scrape_loop, daemon=True)
    scraper.start()
    try:
        result = worker.run()
    finally:
        stop.set()
        scraper.join(5.0)
        srv.stop()
    assert result["tasks_done"] == 3
    # The two 400 ms stalls alone guarantee many scrape windows; every
    # one must have answered.
    assert scrapes["ok"] >= 5 and scrapes["fail"] == 0
    assert worker.gauges.scalar_values(
        [gauge.EXAMPLES_TRAINED]
    )[gauge.EXAMPLES_TRAINED] == 96.0


def test_worker_families_match_the_naming_table_after_a_job(tmp_path, devices):
    """The registry families a real worker publishes cover the whole
    JSONL naming table (the coexistence assert the one-table stance
    hangs on), and the envelope payload carries them."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker

    train = str(tmp_path / "train.rio")
    generate("mnist", train, 64)
    config = JobConfig(
        model_def="mnist.model_spec",
        training_data=train,
        minibatch_size=16,
        num_minibatches_per_task=2,
    )
    reader = create_data_reader(train)
    from elasticdl_tpu.master.servicer import MasterServicer

    servicer = MasterServicer(TaskDispatcher(reader.create_shards(32)))
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices,
    )
    result = worker.run()
    assert result["tasks_done"] == 2
    # force=True: the loop's own final beat may have shipped within the
    # throttle window (the report path uses the same bypass).
    payload = worker.gauge_payload(force=True)
    assert set(gauge.JSONL_GAUGE_FAMILIES) <= set(payload["families"])
    mirror = servicer.fleet.jsonl_mirror("w0", payload)
    assert set(mirror) == set(gauge.JSONL_GAUGE_FAMILIES)
    assert mirror[gauge.EXAMPLES_TRAINED] == 64.0
    assert mirror[gauge.TASKS_DONE] == 2.0
    # The per-phase families rode along (PhaseTimers -> collector).
    fams = payload["families"]
    assert "edl_phase_seconds_total" in fams
    assert fams["edl_phase_ms"]["type"] == "histogram"


# ---------------------------------------------------------------------------
# bench_regress: the trajectory gate
# ---------------------------------------------------------------------------

def _write(repo, name, payload):
    os.makedirs(os.path.join(repo, "artifacts"), exist_ok=True)
    with open(os.path.join(repo, "artifacts", name), "w") as f:
        json.dump(payload, f)


class TestBenchRegress:
    def _bench(self, value, pipeline=None, platform="cpu"):
        d = {
            "metric": "deepfm_criteo_e2e_examples_per_sec_per_chip",
            "value": value,
            "jax_platforms": platform,
        }
        if pipeline is not None:
            d["pipeline"] = pipeline
        return d

    def test_pass_improvement(self, tmp_path):
        from tools.bench_regress import build_trajectory, index_artifacts

        repo = str(tmp_path)
        _write(repo, "bench_r05.json", self._bench(100.0))
        _write(repo, "bench_r06.json", self._bench(150.0))
        t = build_trajectory(index_artifacts(repo), 10.0)
        (series,) = [
            s for s in t["series"] if s["name"] == "e2e_examples_per_sec_per_chip"
        ]
        assert series["status"] == "ok"
        assert series["latest_delta_pct"] == pytest.approx(50.0)
        assert t["regressions"] == []

    def test_fail_regression_and_exit_code(self, tmp_path):
        from tools.bench_regress import main as regress_main

        repo = str(tmp_path)
        _write(repo, "bench_r05.json", self._bench(100.0))
        _write(repo, "bench_r06.json", self._bench(80.0))  # -20%
        rc = regress_main(["--repo", repo, "--threshold", "10"])
        assert rc == 1
        with open(os.path.join(repo, "artifacts", "TRAJECTORY.json")) as f:
            trajectory = json.load(f)
        assert trajectory["regressions"]
        r = trajectory["regressions"][0]
        assert r["delta_pct"] == pytest.approx(-20.0)

    def test_threshold_tolerates_weather(self, tmp_path):
        from tools.bench_regress import build_trajectory, index_artifacts

        repo = str(tmp_path)
        _write(repo, "bench_r05.json", self._bench(100.0))
        _write(repo, "bench_r06.json", self._bench(95.0))  # -5%
        t = build_trajectory(index_artifacts(repo), 10.0)
        assert t["regressions"] == []
        t = build_trajectory(index_artifacts(repo), 3.0)
        assert len(t["regressions"]) == 1

    def test_lower_is_better_direction(self, tmp_path):
        from tools.bench_regress import build_trajectory, index_artifacts

        repo = str(tmp_path)
        point = {"offered_qps": 50.0, "p99_ms": 20.0}
        _write(repo, "SERVE_r10.json", {
            "metric": "serving_latency_vs_qps", "points": [point],
        })
        _write(repo, "SERVE_r11.json", {
            "metric": "serving_latency_vs_qps",
            "points": [{"offered_qps": 50.0, "p99_ms": 40.0}],  # 2x worse
        })
        t = build_trajectory(index_artifacts(repo), 10.0)
        assert len(t["regressions"]) == 1
        assert t["regressions"][0]["name"] == "p99_ms[qps50.0]"

    def test_config_change_skips_comparison(self, tmp_path):
        from tools.bench_regress import build_trajectory, index_artifacts

        repo = str(tmp_path)
        _write(repo, "bench_r05.json",
               self._bench(100.0, pipeline={"lease_batch": 4}))
        _write(repo, "bench_r06.json",
               self._bench(50.0, pipeline={"lease_batch": 1}))
        t = build_trajectory(index_artifacts(repo), 10.0)
        (series,) = [
            s for s in t["series"] if s["name"] == "e2e_examples_per_sec_per_chip"
        ]
        assert series["status"] == "config_changed"
        assert t["regressions"] == []

    def test_missing_config_key_is_unconstrained(self, tmp_path):
        from tools.bench_regress import build_trajectory, index_artifacts

        repo = str(tmp_path)
        _write(repo, "bench_r05.json", self._bench(100.0))  # pre-pipeline rev
        _write(repo, "bench_r06.json",
               self._bench(150.0, pipeline={"lease_batch": 4}))
        t = build_trajectory(index_artifacts(repo), 10.0)
        (series,) = [
            s for s in t["series"] if s["name"] == "e2e_examples_per_sec_per_chip"
        ]
        assert series["status"] == "ok"

    def test_same_rev_keeps_direction_best(self, tmp_path):
        from tools.bench_regress import build_trajectory, index_artifacts

        repo = str(tmp_path)
        _write(repo, "bench_r05.json", self._bench(100.0))
        _write(repo, "bench_r05_latest.json", self._bench(120.0))
        _write(repo, "bench_r06.json", self._bench(115.0))
        t = build_trajectory(index_artifacts(repo), 10.0)
        (series,) = [
            s for s in t["series"] if s["name"] == "e2e_examples_per_sec_per_chip"
        ]
        # 115 vs the r5 RECORD (120), within threshold: ok, slight dip.
        assert series["status"] == "ok"
        assert series["points"][0]["value"] == 120.0

    def test_committed_repo_trajectory_is_nonempty_and_clean(self):
        from tools.bench_regress import build_trajectory, index_artifacts

        t = build_trajectory(index_artifacts(), 10.0)
        assert t["series"], "the committed artifacts must index"
        assert t["compared"] >= 2, "gang_ingest r06->r09 must compare"
        assert t["regressions"] == []

    def test_unreadable_and_own_output_skipped(self, tmp_path):
        from tools.bench_regress import index_artifacts

        repo = str(tmp_path)
        _write(repo, "bench_r05.json", self._bench(100.0))
        _write(repo, "TRAJECTORY.json", {"metric": "cross_rev_perf_trajectory"})
        with open(os.path.join(repo, "artifacts", "broken_r01.json"), "w") as f:
            f.write("{not json")
        entries = index_artifacts(repo)
        assert [e["file"] for e in entries] == ["artifacts/bench_r05.json"]

    def test_parse_name_variants(self):
        from tools.bench_regress import parse_name

        assert parse_name("gang_ingest_r09.json") == ("gang_ingest", 9)
        assert parse_name("LINT_r14.json") == ("LINT", 14)
        assert parse_name("bench_r05_latest.json") == ("bench", 5)
        assert parse_name("ps_bench_r10.json") == ("ps_bench", 10)
        assert parse_name("TRAJECTORY.json") == ("TRAJECTORY", 0)


# ---------------------------------------------------------------------------
# locksan contention bridge (r16): edl_lock_acquire_total / edl_lock_wait_ms
# ---------------------------------------------------------------------------

class TestLockContentionGauges:
    def test_collector_publishes_lock_families(self):
        from elasticdl_tpu.common import locksan

        locksan.reset()
        reg = gauge.Registry()
        collector = gauge.install_lock_collector(reg)
        try:
            lk = locksan.lock("Bridge._lock")
            for _ in range(3):
                with lk:
                    pass
            snap = reg.snapshot()  # collectors run at scrape time
            acq = snap["edl_lock_acquire_total"]["samples"]
            (sample,) = [
                s for s in acq if s["labels"].get("lock") == "Bridge._lock"
            ]
            assert sample["value"] == 3.0
            hist = snap["edl_lock_wait_ms"]
            assert hist["type"] == "histogram"
            (hs,) = [
                s for s in hist["samples"]
                if s["labels"].get("lock") == "Bridge._lock"
            ]
            assert hs["value"]["count"] == 3
            # The shared grid: live scrape buckets match artifact buckets.
            assert tuple(hs["value"]["edges"]) == gauge.DEFAULT_BUCKET_EDGES_MS
            # Re-scrape overwrites with the newer cumulative totals.
            with lk:
                pass
            snap = reg.snapshot()
            (sample,) = [
                s for s in snap["edl_lock_acquire_total"]["samples"]
                if s["labels"].get("lock") == "Bridge._lock"
            ]
            assert sample["value"] == 4.0
        finally:
            reg.remove_collector(collector)
            locksan.reset()

    def test_render_and_watch_job_summary(self):
        from elasticdl_tpu.common import locksan
        from tools.watch_job import parse_prometheus, render_locks

        locksan.reset()
        reg = gauge.Registry()
        collector = gauge.install_lock_collector(reg)
        try:
            with locksan.lock("Watch._lock"):
                pass
            families = parse_prometheus(reg.render_prometheus())
            line = render_locks(families)
            assert line is not None and line.startswith("locks:")
            # Total spans every sanitized lock (the registry's own leaf
            # locks record too once stats are on) — assert presence, not
            # an exact count.
            assert "acquires=" in line
            assert "Watch._lock" in line
        finally:
            reg.remove_collector(collector)
            locksan.reset()

    def test_histogram_load_snapshot_rejects_mismatched_grid(self):
        h = gauge.Histogram()
        with pytest.raises(ValueError):
            h.load_snapshot({"edges": [1.0], "counts": [0, 0], "sum": 0.0,
                             "count": 0})


class TestLintTrajectorySeries:
    def test_lint_findings_series_and_zero_baseline_gate(self, tmp_path):
        from tools.bench_regress import build_trajectory, index_artifacts

        repo = str(tmp_path)
        # Old LINT artifacts predate the "metric" key: the family fallback
        # must index them so the lint-debt series spans revisions.
        _write(repo, "LINT_r15.json", {"findings": 0})
        _write(repo, "LINT_r16.json", {"metric": "lint_findings", "findings": 0})
        t = build_trajectory(index_artifacts(repo), 10.0)
        (series,) = [s for s in t["series"] if s["family"] == "LINT"]
        assert series["direction"] == "lower"
        assert [p["value"] for p in series["points"]] == [0.0, 0.0]
        assert t["regressions"] == []
        # Any climb off the zero baseline is a regression outright.
        _write(repo, "LINT_r17.json", {"metric": "lint_findings", "findings": 2})
        t = build_trajectory(index_artifacts(repo), 10.0)
        (series,) = [s for s in t["series"] if s["family"] == "LINT"]
        assert series["status"] == "REGRESSED"
        assert t["regressions"]
