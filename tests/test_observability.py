"""Observability: metrics JSONL stream from train/eval reports, profiler
trace capture in the worker loop (SURVEY.md §5)."""

import glob
import os

import pytest

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.metrics import MetricsWriter, read_metrics
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.data.synthetic import generate
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker


def test_metrics_writer_roundtrip(tmp_path):
    writer = MetricsWriter(str(tmp_path), tensorboard=False)
    writer.write("train", 3, {"loss": 1.5, "accuracy": 0.5})
    writer.write("eval", 3, {"loss": 1.2})
    writer.close()
    records = read_metrics(str(tmp_path))
    assert len(records) == 2
    assert records[0]["kind"] == "train"
    assert records[0]["step"] == 3
    assert records[0]["loss"] == 1.5
    assert records[1]["kind"] == "eval"


def test_metrics_writer_tensorboard(tmp_path):
    pytest.importorskip("tensorboardX")
    writer = MetricsWriter(str(tmp_path))
    writer.write("train", 1, {"loss": 2.0})
    writer.close()
    events = glob.glob(str(tmp_path / "tensorboard" / "events*"))
    assert events, "expected a tensorboard event file"


def test_read_metrics_missing_dir(tmp_path):
    assert read_metrics(str(tmp_path / "nope")) == []


def test_metrics_writer_holds_one_append_handle(tmp_path):
    """One handle for the stream's life (the old idiom reopened per
    record); records are flushed so a concurrent reader sees them."""
    writer = MetricsWriter(str(tmp_path), tensorboard=False)
    f = writer._f
    writer.write("train", 1, {"loss": 1.0})
    writer.write("train", 2, {"loss": 0.5})
    assert writer._f is f  # same handle across records
    # Flushed: visible to an independent reader before close().
    assert len(read_metrics(str(tmp_path))) == 2
    writer.close()
    assert writer._f is None
    # A report racing close() reopens instead of crashing the handler.
    writer.write("train", 3, {"loss": 0.25})
    writer.close()
    assert len(read_metrics(str(tmp_path))) == 3


def test_read_metrics_tolerates_torn_final_line(tmp_path):
    writer = MetricsWriter(str(tmp_path), tensorboard=False)
    writer.write("train", 1, {"loss": 1.0})
    writer.write("train", 2, {"loss": 0.5})
    writer.close()
    path = tmp_path / "metrics.jsonl"
    # Simulate a crash mid-append: the final line is torn.
    with open(path, "a") as f:
        f.write('{"ts": 3, "kind": "tra')
    records = read_metrics(str(tmp_path))
    assert [r["step"] for r in records] == [1, 2]
    # Garbage EARLIER in the stream is corruption, not a crash tail: raise.
    lines = path.read_text().splitlines()
    lines[0] = "not json {{{"
    path.write_text("\n".join(lines) + "\n")
    with pytest.raises(Exception):
        read_metrics(str(tmp_path))


def _job(tmp_path, **cfg):
    train = str(tmp_path / "train.rio")
    val = str(tmp_path / "val.rio")
    generate("mnist", train, 64)
    generate("mnist", val, 32)
    config = JobConfig(
        model_def="mnist.model_spec",
        model_params="compute_dtype=float32",
        training_data=train,
        validation_data=val,
        minibatch_size=16,
        num_minibatches_per_task=2,
        **cfg,
    )
    reader = create_data_reader(train)
    per_task = config.minibatch_size * config.num_minibatches_per_task
    dispatcher = TaskDispatcher(reader.create_shards(per_task))
    evaluation = EvaluationService(
        create_data_reader(val).create_shards(per_task), evaluation_steps=2
    )
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )
    return config, dispatcher, evaluation, reader, spec


class _MuxReader:
    def __init__(self, *readers):
        self._readers = readers

    def read_records(self, shard):
        for r in self._readers:
            if shard.name in r.sources():
                return r.read_records(shard)
        raise KeyError(shard.name)


def test_master_writes_train_and_eval_metrics(tmp_path, devices):
    config, dispatcher, evaluation, reader, spec = _job(tmp_path)
    writer = MetricsWriter(str(tmp_path / "metrics"), tensorboard=False)
    servicer = MasterServicer(
        dispatcher, evaluation=evaluation, metrics_writer=writer
    )
    val_reader = create_data_reader(str(tmp_path / "val.rio"))
    worker = Worker(
        config,
        DirectMasterProxy(servicer),
        _MuxReader(reader, val_reader),
        spec=spec,
    )
    worker.run()
    writer.close()
    records = read_metrics(str(tmp_path / "metrics"))
    kinds = {r["kind"] for r in records}
    assert "train" in kinds
    assert "eval" in kinds
    train_records = [r for r in records if r["kind"] == "train"]
    assert all("loss" in r for r in train_records)
    # eval rounds recorded once each
    eval_records = [r for r in records if r["kind"] == "eval"]
    assert len(eval_records) == evaluation.completed_rounds()


def test_phase_counts_ride_reports_into_job_status(tmp_path, devices):
    """PhaseTimers.counts() rides ReportTaskResult/ReportCheckpoint beside
    phase_times (additive optional field), and JobStatus republishes it —
    per-phase AVERAGES become computable from the same artifact that held
    only cumulative sums."""
    config, dispatcher, evaluation, reader, spec = _job(tmp_path)
    servicer = MasterServicer(dispatcher)
    worker = Worker(config, DirectMasterProxy(servicer), reader, spec=spec)
    worker.run()
    status = servicer.JobStatus({})
    counts = status["phase_counts"].get(worker.worker_id)
    times = status["phase_times"].get(worker.worker_id)
    assert counts and times
    # Counts key the same phases the seconds do, and each recorded phase
    # entered at least once — total/count is a well-defined mean.
    for name, seconds in times.items():
        assert counts.get(name, 0) >= 1, name
        assert seconds >= 0


def test_worker_profiler_trace(tmp_path, devices):
    prof = str(tmp_path / "prof")
    config, dispatcher, evaluation, reader, spec = _job(
        tmp_path, profile_dir=prof
    )
    servicer = MasterServicer(dispatcher)
    worker = Worker(config, DirectMasterProxy(servicer), reader, spec=spec)
    worker.run()
    traces = glob.glob(os.path.join(prof, "**", "*.xplane.pb"), recursive=True)
    assert traces, "expected an xplane trace from the profiled task"
