"""PodManager unit tests via the fake backend (the reference's mock-k8s
pattern, SURVEY.md §4) plus master-orchestrated jobs: fake-fleet supervision
and a real ProcessPodBackend end-to-end run with a mid-job worker kill."""

import os
import sys
import threading
import time

import pytest

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.data.synthetic import generate
from elasticdl_tpu.master.main import Master
from elasticdl_tpu.master.pod_manager import (
    FakePodBackend,
    PodManager,
    PodPhase,
    ProcessPodBackend,
    render_worker_pod_manifest,
)


def _manager(num_workers=4, max_relaunch=2, relaunch=True):
    backend = FakePodBackend()
    config = JobConfig(
        job_name="job",
        num_workers=num_workers,
        relaunch_on_worker_failure=relaunch,
        max_worker_relaunch=max_relaunch,
    )
    manager = PodManager(backend, config)
    return manager, backend


class TestPodManager:
    def test_start_launches_desired_pods(self):
        manager, backend = _manager(num_workers=4)
        manager.start()
        assert len(backend.running()) == 4
        assert manager.live_pods() == [f"job-worker-{i}" for i in range(4)]

    def test_failed_pod_is_relaunched_with_fresh_name(self):
        manager, backend = _manager(num_workers=2)
        manager.start()
        backend.fail_pod("job-worker-0")
        assert "job-worker-0-r1" in backend.running()
        assert len(manager.live_pods()) == 2
        backend.fail_pod("job-worker-0-r1")
        assert "job-worker-0-r2" in backend.running()

    def test_relaunch_budget_exhausted(self):
        manager, backend = _manager(num_workers=1, max_relaunch=1)
        manager.start()
        backend.fail_pod("job-worker-0")
        backend.fail_pod("job-worker-0-r1")
        assert manager.live_pods() == []
        assert manager.all_finished()

    def test_no_relaunch_when_disabled(self):
        manager, backend = _manager(num_workers=1, relaunch=False)
        manager.start()
        backend.fail_pod("job-worker-0")
        assert manager.live_pods() == []

    def test_scale_up_and_down(self):
        manager, backend = _manager(num_workers=4)
        manager.start()
        manager.scale(8)
        assert len(manager.live_pods()) == 8
        manager.scale(4)
        assert manager.live_pods() == [f"job-worker-{i}" for i in range(4)]
        # Retired pods got real delete calls, not silent forgetting.
        assert backend.pods["job-worker-7"] == PodPhase.DELETED

    def test_succeeded_pod_not_relaunched(self):
        manager, backend = _manager(num_workers=2)
        manager.start()
        backend.succeed_pod("job-worker-0")
        assert manager.live_pods() == ["job-worker-1"]

    def test_listener_sees_events(self):
        manager, backend = _manager(num_workers=2)
        events = []
        manager.add_listener(lambda name, phase: events.append((name, phase)))
        manager.start()
        backend.fail_pod("job-worker-1")
        assert ("job-worker-1", PodPhase.FAILED) in events

    def test_worker_env_carries_config_and_identity(self):
        backend = FakePodBackend()
        config = JobConfig(job_name="j", num_workers=1)
        seen = {}
        orig = backend.start_pod

        def spy(name, env):
            seen[name] = env
            orig(name, env)

        backend.start_pod = spy
        PodManager(backend, config).start()
        env = seen["j-worker-0"]
        assert env["ELASTICDL_WORKER_ID"] == "j-worker-0"
        assert "ELASTICDL_JOB_CONFIG" in env
        assert JobConfig.from_env(env).job_name == "j"


class TestPodReattach:
    """r18 master crash survivability: the pod registry lets a restarted
    master ADOPT the previous master's live worker orphans instead of
    spawning a duplicate fleet, and resolves their unknowable exit codes
    against job state."""

    @staticmethod
    def _sleep_backend(log_dir=None):
        return ProcessPodBackend(
            argv=[sys.executable, "-c", "import time; time.sleep(60)"],
            poll_interval_s=0.05,
        )

    def _config(self, n=1):
        return JobConfig(job_name="rejob", num_workers=n, max_worker_relaunch=1)

    def test_registry_persists_and_restart_adopts(self, tmp_path):
        state = str(tmp_path / "pod_registry.json")
        b1 = self._sleep_backend()
        m1 = PodManager(b1, self._config(), state_path=state)
        m1.start(1)
        pid = b1.pid("rejob-worker-0")
        assert pid is not None and os.path.exists(state)
        import json

        reg = json.load(open(state))
        assert reg["slots"]["0"]["pid"] == pid
        # "Crash": the first manager/backend go away WITHOUT delete_pod —
        # only the subprocess handle dies, the process lives on.
        b1._stop.set()
        with b1._lock:
            b1._procs.clear()  # simulate the master process dying

        events = []
        b2 = self._sleep_backend()
        m2 = PodManager(b2, self._config(), state_path=state)
        m2.add_listener(lambda name, phase: events.append((name, phase)))
        m2.start(1)
        # Adopted, not respawned: same name, same pid, RUNNING emitted.
        assert b2.pid("rejob-worker-0") == pid
        with b2._lock:
            assert b2._adopted == {"rejob-worker-0": pid}
            assert not b2._procs  # nothing spawned
        assert ("rejob-worker-0", PodPhase.RUNNING) in events
        m2.stop()
        assert not os.path.exists(state)  # clean stop clears the registry
        # stop() killed the adopted orphan too (pid_alive is zombie-aware:
        # in THIS harness the "orphan" is our own unreaped child, a case
        # production adoption never sees — real orphans reap via init).
        from elasticdl_tpu.master.pod_manager import pid_alive

        deadline = time.time() + 5
        while time.time() < deadline and pid_alive(pid):
            time.sleep(0.05)
        assert not pid_alive(pid)

    def test_dead_registry_pid_falls_through_to_spawn(self, tmp_path):
        state = str(tmp_path / "pod_registry.json")
        import json

        json.dump(
            {"slots": {"0": {"name": "rejob-worker-0-r2", "pid": 2 ** 22 + 1234,
                             "relaunches": 2, "gen": 2}}},
            open(state, "w"),
        )
        b = self._sleep_backend()
        m = PodManager(b, self._config(), state_path=state)
        m.start(1)
        with b._lock:
            assert not b._adopted
            assert len(b._procs) == 1  # normal spawn
            # The dead generation's gen still seeds the slot: the fresh
            # pod must NOT reuse the dead incarnation's exact name (late
            # events and worker-id collisions would alias to it).
            (name,) = b._procs
        assert name == "rejob-worker-0-r3"
        m.stop()

    def test_lost_resolves_failed_before_finish_succeeded_after(self, tmp_path):
        import subprocess

        state = str(tmp_path / "pod_registry.json")
        orphan = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"]
        )
        try:
            import json

            json.dump(
                {"slots": {"0": {"name": "rejob-worker-0", "pid": orphan.pid,
                                 "relaunches": 0, "gen": 0}}},
                open(state, "w"),
            )
            b = self._sleep_backend()
            m = PodManager(b, self._config(), state_path=state)
            finished = {"v": False}
            m.set_job_finished_fn(lambda: finished["v"])
            events = []
            m.add_listener(lambda name, phase: events.append((name, phase)))
            m.start(1)
            with b._lock:
                assert b._adopted == {"rejob-worker-0": orphan.pid}
            # Kill the orphan while the job is NOT finished: LOST resolves
            # to FAILED and the slot relaunches (budget charged).
            orphan.kill()
            orphan.wait()
            deadline = time.time() + 10
            while time.time() < deadline and not any(
                p == PodPhase.FAILED for _n, p in events
            ):
                time.sleep(0.05)
            assert ("rejob-worker-0", PodPhase.FAILED) in events
            deadline = time.time() + 10
            while time.time() < deadline:
                with b._lock:
                    if b._procs:  # the relaunch spawned
                        break
                time.sleep(0.05)
            info = m.pod_info("rejob-worker-0-r1")
            assert info is not None and info.relaunches == 1
            m.stop()
        finally:
            if orphan.poll() is None:
                orphan.kill()

    def test_lost_after_job_end_resolves_succeeded(self, tmp_path):
        import json
        import subprocess

        state = str(tmp_path / "pod_registry.json")
        orphan = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"]
        )
        try:
            json.dump(
                {"slots": {"0": {"name": "rejob-worker-0", "pid": orphan.pid,
                                 "relaunches": 0, "gen": 0}}},
                open(state, "w"),
            )
            b = self._sleep_backend()
            m = PodManager(b, self._config(), state_path=state)
            m.set_job_finished_fn(lambda: True)  # the job is already done
            events = []
            m.add_listener(lambda name, phase: events.append((name, phase)))
            m.start(1)
            orphan.kill()
            orphan.wait()
            # A disappearance AFTER the job finished IS the worker's
            # clean exit: SUCCEEDED, slot retired, no relaunch.
            deadline = time.time() + 10
            while time.time() < deadline and (
                ("rejob-worker-0", PodPhase.SUCCEEDED) not in events
            ):
                time.sleep(0.05)
            assert ("rejob-worker-0", PodPhase.SUCCEEDED) in events
            assert m.all_finished()
            with b._lock:
                assert not b._procs  # nothing relaunched
            m.stop()
        finally:
            if orphan.poll() is None:
                orphan.kill()


class TestPodManifest:
    def test_tpu_pod_manifest_shape(self):
        config = JobConfig(job_name="deepfm")
        manifest = render_worker_pod_manifest(
            config, "deepfm-worker-0", {"A": "1"}, tpu_chips_per_host=4
        )
        assert manifest["kind"] == "Pod"
        container = manifest["spec"]["containers"][0]
        assert container["resources"]["limits"]["google.com/tpu"] == "4"
        selector = manifest["spec"]["nodeSelector"]
        assert "cloud.google.com/gke-tpu-topology" in selector
        assert manifest["spec"]["restartPolicy"] == "Never"
        assert {"name": "A", "value": "1"} in container["env"]

    def test_ps_pod_manifest_shape(self):
        """PS shard pods: CPU-only, stable per-SLOT hostname under the
        headless <job>-ps subdomain (a relaunched shard keeps its DNS name
        even though the pod name carries a generation suffix)."""
        from elasticdl_tpu.master.pod_manager import render_ps_pod_manifest

        config = JobConfig(job_name="deepfm")
        manifest = render_ps_pod_manifest(
            config, "deepfm-ps-1-r2", {"ELASTICDL_WORKER_SLOT": "1"}
        )
        container = manifest["spec"]["containers"][0]
        assert "resources" not in container  # no TPU request
        assert "nodeSelector" not in manifest["spec"]
        assert manifest["spec"]["hostname"] == "deepfm-ps-1"
        assert manifest["spec"]["subdomain"] == "deepfm-ps"
        assert container["command"] == [
            "python", "-m", "elasticdl_tpu.ps.main"
        ]
        labels = manifest["metadata"]["labels"]
        assert labels["elasticdl-replica-type"] == "ps"


def _job_config(tmp_path, **kwargs):
    train = str(tmp_path / "train.rio")
    generate("mnist", train, 64)
    return JobConfig(
        model_def="mnist.model_spec",
        model_params="compute_dtype=float32",
        training_data=train,
        minibatch_size=16,
        num_minibatches_per_task=1,
        **kwargs,
    )


class TestMasterWithFakeFleet:
    def test_fleet_death_fails_job(self, tmp_path):
        config = _job_config(
            tmp_path, num_workers=1, max_worker_relaunch=0,
            relaunch_on_worker_failure=False,
        )
        backend = FakePodBackend()
        master = Master(config, pod_backend=backend)
        errors = []

        def run():
            try:
                master.run(poll_interval_s=0.05, reap_every_s=0.5)
            except RuntimeError as e:
                errors.append(e)

        t = threading.Thread(target=run)
        t.start()
        time.sleep(0.2)
        backend.fail_pod(f"{config.job_name}-worker-0")
        t.join(timeout=10)
        assert not t.is_alive()
        assert errors and "terminated before the job finished" in str(errors[0])

    def test_pod_failure_bumps_membership(self, tmp_path):
        config = _job_config(tmp_path, num_workers=2)
        backend = FakePodBackend()
        master = Master(config, pod_backend=backend)
        master.pod_manager.start()
        master.rendezvous.register(f"{config.job_name}-worker-0")
        master.rendezvous.register(f"{config.job_name}-worker-1")
        v = master.rendezvous.version()
        backend.fail_pod(f"{config.job_name}-worker-1")
        assert master.rendezvous.version() > v
        # The relaunched pod re-registers itself when it comes up.
        assert f"{config.job_name}-worker-1-r1" in backend.running()
        master.shutdown()


WORKER_PY = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
from elasticdl_tpu.worker.main import main
{hook}
sys.exit(main())
"""

CRASH_HOOK = """
# Crash the FIRST generation mid-task to exercise relaunch: the relaunched
# process sees the marker file and runs clean.
import elasticdl_tpu.worker.worker as W
marker = os.environ["CRASH_MARKER"]
if not os.path.exists(marker):
    open(marker, "w").close()
    _orig = W.Worker._run_training_task
    def _boom(self, task):
        os.kill(os.getpid(), 9)
    W.Worker._run_training_task = _boom
"""


def _process_backend(tmp_path, hook=""):
    script = tmp_path / "worker_entry.py"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script.write_text(WORKER_PY.format(repo=repo, hook=hook))
    return ProcessPodBackend(argv=[sys.executable, str(script)])


@pytest.mark.slow
class TestMasterProcessJob:
    def test_end_to_end_subprocess_job(self, tmp_path):
        config = _job_config(tmp_path, num_workers=2)
        master = Master(config, pod_backend=_process_backend(tmp_path))
        status = master.run(poll_interval_s=0.1)
        assert status["finished"]
        assert status["done"] == 4  # 64 records / 16-record tasks
        # model_version is the max of per-worker local step counters; with the
        # 4 tasks split across 2 workers it lands in [2, 4].
        assert 2 <= status["model_version"] <= 4

    def test_worker_crash_relaunch_completes_job(self, tmp_path):
        config = _job_config(tmp_path, num_workers=1, max_worker_relaunch=2)
        backend = _process_backend(tmp_path, hook=CRASH_HOOK)
        marker = str(tmp_path / "crashed.marker")
        os.environ["CRASH_MARKER"] = marker
        try:
            master = Master(config, pod_backend=backend)
            status = master.run(poll_interval_s=0.1)
        finally:
            os.environ.pop("CRASH_MARKER", None)
        assert os.path.exists(marker)  # the crash really happened
        assert status["finished"] and status["done"] == 4


# ---------------------------------------------------------------------------
# k8s watch-event mapping (VERDICT r3 item 8): synthetic events through the
# same mapping/loop the in-cluster watcher drives, no cluster needed.
# ---------------------------------------------------------------------------


def _fake_pod(name, phase, exit_code=None, broken=False):
    from types import SimpleNamespace as NS

    if broken:
        # attribute access explodes like a half-populated API object
        class Boom:
            @property
            def container_statuses(self):
                raise AttributeError("partial API object")

            phase = PodPhase.FAILED
        status = Boom()
    elif exit_code is None:
        status = NS(phase=phase, container_statuses=None)
    else:
        status = NS(
            phase=phase,
            container_statuses=[NS(state=NS(terminated=NS(exit_code=exit_code)))],
        )
    return {"object": NS(metadata=NS(name=name), status=status)}


def test_map_watch_event_phases():
    from elasticdl_tpu.master.pod_manager import (
        WORKER_RESTART_EXIT_CODE,
        map_watch_event,
    )

    assert map_watch_event(_fake_pod("w0", "Running")) == ("w0", PodPhase.RUNNING)
    assert map_watch_event(_fake_pod("w0", "Succeeded")) == (
        "w0", PodPhase.SUCCEEDED,
    )
    # Failed + RESTART exit code -> budget-free RESTART
    assert map_watch_event(
        _fake_pod("w1", "Failed", exit_code=WORKER_RESTART_EXIT_CODE)
    ) == ("w1", PodPhase.RESTART)
    # Failed + real failure exit code -> FAILED (consumes relaunch budget)
    assert map_watch_event(_fake_pod("w2", "Failed", exit_code=1)) == (
        "w2", PodPhase.FAILED,
    )
    # Failed with no container statuses -> FAILED
    assert map_watch_event(_fake_pod("w3", "Failed")) == ("w3", PodPhase.FAILED)
    # Half-populated API object: mapping must not raise, stays FAILED
    assert map_watch_event(_fake_pod("w4", "Failed", broken=True)) == (
        "w4", PodPhase.FAILED,
    )


def test_run_watch_loop_reestablishes_and_feeds_slots():
    """The loop survives a stream that dies mid-watch (410 Gone analogue)
    and keeps emitting; RESTART events reach the PodManager relaunch logic
    without consuming the failure budget (wired end-to-end elsewhere via
    FakePodBackend — here we pin the k8s-side mapping feeding _emit)."""
    import threading

    from elasticdl_tpu.master.pod_manager import (
        WORKER_RESTART_EXIT_CODE,
        run_watch_loop,
    )

    stop = threading.Event()
    seen = []
    rounds = []

    def stream_factory():
        rounds.append(1)
        if len(rounds) == 1:
            def first():
                yield _fake_pod("w0", "Running")
                raise RuntimeError("410 Gone")
            return first()

        def second():
            yield _fake_pod("w0", "Failed", exit_code=WORKER_RESTART_EXIT_CODE)
            stop.set()
            yield _fake_pod("w9", "Running")  # consumed; loop exits after
        return second()

    run_watch_loop(stream_factory, lambda n, p: seen.append((n, p)), stop,
                   backoff_s=0.01)
    assert ("w0", PodPhase.RUNNING) in seen
    assert ("w0", PodPhase.RESTART) in seen
    assert len(rounds) == 2


# ---------------------------------------------------------------------------
# Warm-standby spare (VERDICT r4 Next #4b): the backend parks one pre-booted
# process and hands it its worker id via the go-file at relaunch time.
# ---------------------------------------------------------------------------

STANDBY_STUB = """
import json, os, sys, time
go = os.environ.get("ELASTICDL_STANDBY_GO_FILE")
out = os.environ["STANDBY_TEST_OUT"]
if go:
    # Mirror worker.main's standby protocol: a configurable "import
    # warmup", then the atomic readiness marker adoption gates on.
    time.sleep(float(os.environ.get("STANDBY_WARMUP_S", "0")))
    with open(go + ".ready.tmp", "w") as f:
        f.write(str(os.getpid()))
    os.replace(go + ".ready.tmp", go + ".ready")
    while not os.path.exists(go):
        time.sleep(0.01)
    payload = json.loads(open(go).read())
    for k, v in payload.get("env", {}).items():
        os.environ[k] = v
    wid = payload["worker_id"]
    mode = "warm"
else:
    wid = os.environ["ELASTICDL_WORKER_ID"]
    mode = "cold"
slot = os.environ.get("ELASTICDL_WORKER_SLOT", "?")
# Atomic marker: the test polls for this file's EXISTENCE, so a plain
# open-then-write can be observed empty on a starved box.
marker = os.path.join(out, f"ran.{wid}")
with open(marker + ".tmp", "w") as f:
    f.write(f"{mode}:{os.getpid()}:{slot}")
os.replace(marker + ".tmp", marker)
time.sleep(60)  # stay 'running' like a real worker
"""


def _wait(cond, timeout=15.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _spare_ready(backend) -> bool:
    """A parked spare exists AND has published its readiness marker (the
    adoption gate)."""
    with backend._lock:
        spares = list(backend._standby)
    return any(os.path.exists(go + ".ready") for _, go, _ in spares)


def test_warm_standby_adopted_on_relaunch(tmp_path):
    script = tmp_path / "stub.py"
    script.write_text(STANDBY_STUB)
    backend = ProcessPodBackend(
        argv=[sys.executable, str(script)], warm_standby=True
    )
    def env(name, slot, **extra):
        # Mirrors PodManager._pod_env: per-pod identity + job-static env.
        return {
            "ELASTICDL_WORKER_ID": name,
            "ELASTICDL_WORKER_SLOT": str(slot),
            "STANDBY_TEST_OUT": str(tmp_path),
            **extra,
        }

    try:
        backend.start_pod("w-0", env("w-0", 0))  # cold (no spare) + parks one
        _wait(lambda: (tmp_path / "ran.w-0").exists(), what="w-0 boot")
        assert (tmp_path / "ran.w-0").read_text().split(":") [::2] == [
            "cold", "0",
        ]
        _wait(lambda: _spare_ready(backend), what="spare parked + ready")
        spare_pid = backend._standby[0][0].pid

        # Adoption works across SLOTS (review r5: per-pod slot must ride the
        # go file, not the spawn signature) — relaunch slot 1 from the spare
        # parked by slot 0's launch.
        backend.start_pod("w-1", env("w-1", 1))
        _wait(lambda: (tmp_path / "ran.w-1").exists(), what="w-1 adoption")
        mode, pid, slot = (tmp_path / "ran.w-1").read_text().split(":")
        assert (mode, slot) == ("warm", "1") and int(pid) == spare_pid
        # A replacement spare was parked for the NEXT relaunch.
        _wait(
            lambda: len(backend._standby) == 1
            and backend._standby[0][0].pid != spare_pid,
            what="replacement spare",
        )

        # Job-static env change invalidates the spare: next launch is cold.
        backend.start_pod("w-2", env("w-2", 2, EXTRA="x"))
        _wait(lambda: (tmp_path / "ran.w-2").exists(), what="w-2 boot")
        assert (tmp_path / "ran.w-2").read_text().startswith("cold:")
        standby_dir = backend._standby_dir
        assert standby_dir is not None and os.path.isdir(standby_dir)
    finally:
        backend.close()
    # close() reaps the spares AND their scratch dir — nothing outlives
    # the job.
    assert backend._standby == []
    assert not os.path.isdir(standby_dir)


def test_dead_spare_falls_back_to_cold_spawn(tmp_path):
    """A spare that died while parked must not be adopted — the launch
    degrades to a cold spawn (spares are latency, never correctness)."""
    script = tmp_path / "stub.py"
    script.write_text(STANDBY_STUB)
    backend = ProcessPodBackend(
        argv=[sys.executable, str(script)], warm_standby=True
    )
    env = {
        "ELASTICDL_WORKER_ID": "w-0",
        "ELASTICDL_WORKER_SLOT": "0",
        "STANDBY_TEST_OUT": str(tmp_path),
    }
    try:
        backend.start_pod("w-0", env)
        _wait(lambda: _spare_ready(backend), what="spare parked + ready")
        backend._standby[0][0].kill()  # the spare dies while parked
        backend._standby[0][0].wait(timeout=10)

        env2 = dict(env, ELASTICDL_WORKER_ID="w-1", ELASTICDL_WORKER_SLOT="1")
        backend.start_pod("w-1", env2)
        _wait(lambda: (tmp_path / "ran.w-1").exists(), what="w-1 boot")
        assert (tmp_path / "ran.w-1").read_text().startswith("cold:")
        # And the pool healed itself with a fresh live spare.
        _wait(
            lambda: len(backend._standby) == 1
            and backend._standby[0][0].poll() is None,
            what="pool refilled",
        )
    finally:
        backend.close()


def test_standby_churn_two_kills_first_warm_second_cold(tmp_path):
    """Back-to-back kills against a pool of ONE: the first relaunch
    splices the parked spare in, the second (pool still refilling or
    drained) degrades to a cold spawn, and the pool refills behind both —
    spares are latency, never a correctness dependency.  The standby
    lifecycle instants (standby:spawn/adopt/refill) make the whole cycle
    attributable in a merged trace."""
    from elasticdl_tpu.common import trace

    script = tmp_path / "stub.py"
    script.write_text(STANDBY_STUB)
    backend = ProcessPodBackend(
        argv=[sys.executable, str(script)], warm_standby=True,
        standby_pool=1,
    )

    def env(name, slot):
        return {
            "ELASTICDL_WORKER_ID": name,
            "ELASTICDL_WORKER_SLOT": str(slot),
            "STANDBY_TEST_OUT": str(tmp_path),
            # A visible "import warmup": the refill spare spawned behind
            # the first adoption is NOT ready when the second relaunch
            # arrives, which is exactly the burst-beyond-the-pool case.
            "STANDBY_WARMUP_S": "1.0",
        }

    trace.configure(enabled=True)
    trace.default().clear()
    try:
        backend.start_pod("w-0", env("w-0", 0))
        backend.start_pod("w-1", env("w-1", 1))
        _wait(lambda: (tmp_path / "ran.w-0").exists(), what="w-0 boot")
        _wait(lambda: (tmp_path / "ran.w-1").exists(), what="w-1 boot")
        _wait(lambda: _spare_ready(backend), what="spare parked + ready")
        spare_pid = backend._standby[0][0].pid

        # Kill both ranks back-to-back, then relaunch both immediately —
        # the second relaunch arrives while the pool holds at most the
        # one spare the first relaunch is about to take.
        for name in ("w-0", "w-1"):
            with backend._lock:
                proc = backend._procs[name]
            proc.kill()
            proc.wait(timeout=10)
        backend.start_pod("w-0-r1", env("w-0-r1", 0))
        backend.start_pod("w-1-r1", env("w-1-r1", 1))
        _wait(lambda: (tmp_path / "ran.w-0-r1").exists(), what="w-0-r1 boot")
        _wait(lambda: (tmp_path / "ran.w-1-r1").exists(), what="w-1-r1 boot")
        first = (tmp_path / "ran.w-0-r1").read_text().split(":")
        second = (tmp_path / "ran.w-1-r1").read_text().split(":")
        # First splices the parked spare (same pid), second went cold.
        assert first[0] == "warm" and int(first[1]) == spare_pid
        assert second[0] == "cold"
        # The pool healed behind the churn.
        _wait(lambda: backend.standby_depth() == 1, what="pool refilled")

        names = [e["name"] for e in trace.default().export()]
        assert "standby:spawn" in names     # initial park
        assert "standby:adopt" in names     # the splice
        assert "standby:refill" in names    # the post-adoption top-up
        # The splice timeline's adopt stage rides the same moment.
        splices = [
            e for e in trace.default().export()
            if e["name"] == "elastic:splice"
        ]
        assert any(e["args"]["stage"] == "adopt" for e in splices)
    finally:
        trace.configure(enabled=False)
        trace.default().clear()
        backend.close()
