"""Host-tier embedding integration (VERDICT r2 Missing #3 / task 4): tables
too large for HBM live in the native C++ store (ps/native); the trainer
pulls unique rows pre-step, injects them into the jitted step, and pushes
the sparse cotangents post-step for the store's server-side optimizer.

The store itself (numerics, checkpoint, optimizers) is covered by
tests/test_host_store.py; these tests cover the TRAINING integration."""

import numpy as np
import pytest

from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.parallel.trainer import Trainer

pytestmark = pytest.mark.skipif(
    not __import__(
        "elasticdl_tpu.ps.host_store", fromlist=["native_lib_available"]
    ).native_lib_available(),
    reason="native host store unavailable (g++ build failed)",
)


def _host_spec(buckets=512, dim=4, hidden=(16,)):
    return load_model_spec(
        "elasticdl_tpu.models",
        "deepfm.model_spec",
        compute_dtype="float32",
        buckets_per_feature=buckets,
        embedding_dim=dim,
        hidden=hidden,
        host_tier=True,
    )


def _batch(rng, n=32):
    return {
        "dense": rng.uniform(0, 100, size=(n, 13)).astype(np.float32),
        "cat": rng.integers(0, 1 << 30, size=(n, 26)).astype(np.int32),
        "labels": rng.integers(0, 2, size=(n,)).astype(np.int32),
    }


def test_host_ids_match_device_hash():
    """The host-side numpy id function must reproduce the on-device hash
    bit-for-bit, or pulls would fetch the wrong rows."""
    import jax

    from elasticdl_tpu.models.tabular import (
        fuse_feature_ids,
        fuse_feature_ids_np,
    )

    cat = np.random.default_rng(0).integers(0, 1 << 30, size=(64, 26)).astype(np.int32)
    dev = np.asarray(jax.jit(lambda c: fuse_feature_ids(c, 65536))(cat))
    host = fuse_feature_ids_np(cat, 65536)
    np.testing.assert_array_equal(host, dev)


def test_auto_promotion_by_hbm_guard():
    """buckets 2^24 -> 26 x 16.7M rows x stride 16: far past the HBM guard;
    "auto" promotes the table to the host tier, so init allocates NO device
    table and the spec carries host_io instead of embedding_tables."""
    import jax

    spec = load_model_spec(
        "elasticdl_tpu.models",
        "deepfm.model_spec",
        compute_dtype="float32",
        buckets_per_feature=1 << 24,
        embedding_dim=8,
        hidden=(16,),
        host_tier="auto",
    )
    assert spec.host_io and not spec.embedding_tables
    params = jax.eval_shape(spec.init, jax.random.key(0))
    assert "fm_table" not in params  # no device allocation for 436M rows
    # small vocab stays on the mesh
    small = load_model_spec(
        "elasticdl_tpu.models",
        "deepfm.model_spec",
        buckets_per_feature=512,
        host_tier="auto",
    )
    assert small.embedding_tables and not small.host_io


def test_guard_exceeding_table_trains(devices):
    """The done-criterion: a DeepFM variant whose table exceeds the HBM
    guard trains (loss falls), with rows materializing lazily in the C++
    store — only the touched rows exist."""
    import jax

    spec = load_model_spec(
        "elasticdl_tpu.models",
        "deepfm.model_spec",
        compute_dtype="float32",
        buckets_per_feature=1 << 24,  # 436M logical rows: HBM-impossible
        embedding_dim=8,
        hidden=(16,),
        host_tier="auto",
    )
    assert spec.host_io
    trainer = Trainer(
        spec,
        JobConfig(distribution_strategy=DistributionStrategy.PARAMETER_SERVER),
        create_mesh(devices),
    )
    state = trainer.init_state(jax.random.key(0))
    rng = np.random.default_rng(0)
    batch = _batch(rng)
    losses = []
    for _ in range(8):
        state, metrics = trainer.run_train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses
    store = trainer._host_stores["__host__fm_table"]
    # only the batch's distinct ids materialized, not 436M rows
    n_ids = len(np.unique(np.asarray(
        spec.host_io["__host__fm_table"].ids_fn(batch)
    )))
    assert len(store) == n_ids


def test_host_tier_matches_device_tier_forward(devices):
    """Freshly-initialized host rows produce the same MODEL STRUCTURE as the
    device path: eval metrics finite, predictions shaped per-example."""
    import jax

    spec = _host_spec()
    trainer = Trainer(
        spec,
        JobConfig(distribution_strategy=DistributionStrategy.PARAMETER_SERVER),
        create_mesh(devices),
    )
    state = trainer.init_state(jax.random.key(0))
    batch = _batch(np.random.default_rng(1))
    metrics = trainer.run_eval_step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    out = trainer.run_predict_step(state, batch)
    assert np.asarray(out).shape == (32,)


def test_host_store_checkpoint_roundtrip(tmp_path, devices):
    """save_host_stores/restore_host_stores alongside Orbax: trained rows
    survive into a fresh trainer."""
    import jax

    spec = _host_spec()
    config = JobConfig(distribution_strategy=DistributionStrategy.PARAMETER_SERVER)
    trainer = Trainer(spec, config, create_mesh(devices))
    state = trainer.init_state(jax.random.key(0))
    batch = _batch(np.random.default_rng(2))
    for _ in range(3):
        state, _ = trainer.run_train_step(state, batch)
    key = "__host__fm_table"
    ids = spec.host_io[key].ids_fn(batch)
    before = trainer._host_stores[key].pull(ids)
    trainer.save_host_stores(str(tmp_path), 3)

    fresh = Trainer(_host_spec(), config, create_mesh(devices))
    assert fresh.restore_host_stores(str(tmp_path), 3)
    np.testing.assert_array_equal(fresh._host_stores[key].pull(ids), before)
    # A missing snapshot is a torn checkpoint: strict mode (the restore
    # path's default) fails loud, non-strict reports False.
    with pytest.raises(FileNotFoundError, match="torn"):
        fresh.restore_host_stores(str(tmp_path), 99)
    assert not fresh.restore_host_stores(str(tmp_path), 99, strict=False)


def test_host_store_snapshot_retention(tmp_path, devices):
    """save_host_stores prunes old step dirs like Orbax retention does."""
    import jax

    spec = _host_spec()
    config = JobConfig(distribution_strategy=DistributionStrategy.PARAMETER_SERVER)
    trainer = Trainer(spec, config, create_mesh(devices))
    state = trainer.init_state(jax.random.key(0))
    state, _ = trainer.run_train_step(state, _batch(np.random.default_rng(3)))
    for step in (1, 2, 3, 4, 5):
        trainer.save_host_stores(str(tmp_path), step, keep_max=3)
    import os

    kept = sorted(os.listdir(tmp_path / "host_stores"))
    assert kept == ["3", "4", "5"]


def test_torn_checkpoint_falls_back_to_older_step(tmp_path, devices):
    """A crash can commit the Orbax half of step N without its host-store
    snapshot.  The worker's join walks retained steps newest-first and
    adopts the newest INTACT pair instead of crashing or starting over."""
    import shutil

    import jax

    from elasticdl_tpu.common.checkpoint import CheckpointManager
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker

    spec = _host_spec()
    config = JobConfig(
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    trainer = Trainer(spec, config, create_mesh(devices))
    state = trainer.init_state(jax.random.key(0))
    batch = _batch(np.random.default_rng(4))
    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    for step in (1, 2):
        state, _ = trainer.run_train_step(state, batch)
        ckpt.save(step, jax.device_get(state), wait=True)
        trainer.save_host_stores(ckpt.directory, step)
    ckpt.close()
    # Tear step 2: orbax half exists, host half gone (crash mid-write).
    shutil.rmtree(tmp_path / "ckpt" / "host_stores" / "2")

    servicer = MasterServicer(TaskDispatcher([]))  # no tasks: join then exit
    servicer.ReportCheckpoint({"path": str(tmp_path / "ckpt"), "step": 2})
    worker = Worker(
        config, DirectMasterProxy(servicer),
        reader=None, worker_id="w0", spec=_host_spec(), devices=devices,
    )
    result = worker.run()
    assert result["step"] == 1  # fell back to the intact step, not 2, not 0


def test_host_tier_under_sequence_parallelism(devices):
    """Host-tier tables now work for sequence-parallel models on
    single-process meshes: per-token rows are pulled host-side for the full
    batch, the injected [B, S, dim] leaf shards its sequence dim like any
    other batch leaf, and the cotangents come back sequence-sharded for the
    push.  Losses match a 1-device (unsharded) run exactly."""
    import jax
    import jax.numpy as jnp
    import optax

    from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
    from elasticdl_tpu.models.spec import HostTableIO, ModelSpec
    from elasticdl_tpu.parallel.mesh import create_mesh
    from elasticdl_tpu.parallel.trainer import Trainer

    DIM, VOCAB, S, B = 4, 64, 16, 2
    KEY = "__host__tok_emb"

    def apply(params, batch, train=False, ctx=None, **_):
        # Injected per-token rows -> linear head; positions are irrelevant
        # to the routing being tested.
        h = batch[KEY].astype(jnp.float32)            # [B, S_local, DIM]
        return h @ params["w"]                        # [B, S_local, 2]

    def loss(out, batch):
        return optax.softmax_cross_entropy_with_integer_labels(
            out.reshape(-1, 2), batch["labels"].reshape(-1)
        ).mean()

    spec = ModelSpec(
        name="sp_host_toy",
        init=lambda rng: {"w": jax.random.normal(rng, (DIM, 2)) * 0.1},
        apply=apply,
        loss=loss,
        metrics=lambda out, batch: {
            "loss": loss(out, batch),
        },
        optimizer=optax.sgd(0.1),
        host_io={
            KEY: HostTableIO(
                ids_fn=lambda b: b["tokens"], dim=DIM, optimizer="sgd",
                learning_rate=0.5, per_token=True,
            )
        },
        batch_shard_dim=1,
    )
    rng = np.random.RandomState(0)
    # One batch repeated: per-token memorization via the host rows makes the
    # loss strictly decrease, proving the pushes land.
    batch = {
        "tokens": rng.randint(0, VOCAB, (B, S)).astype(np.int64),
        "labels": rng.randint(0, 2, (B, S)).astype(np.int32),
    }
    batches = [batch] * 3
    cfg = JobConfig(distribution_strategy=DistributionStrategy.PARAMETER_SERVER)

    def run(mesh):
        tr = Trainer(spec, cfg, mesh)
        st = tr.init_state(jax.random.key(0))
        out = []
        for b in batches:
            st, m = tr.run_train_step(st, dict(b))
            out.append(float(m["loss"]))
        return out

    unsharded = run(create_mesh(devices[:1]))   # SP axis of 1 = plain run
    sp8 = run(create_mesh(devices))             # 8-way sequence sharding
    np.testing.assert_allclose(sp8, unsharded, rtol=1e-5)
    assert sp8[-1] < sp8[0]
    # Hierarchical SP (dp x ep) works too, single-process.
    hier = run(create_mesh(devices, dcn_parallelism=2))
    np.testing.assert_allclose(hier, unsharded, rtol=1e-5)
