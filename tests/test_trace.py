"""grafttrace (common/trace.py): ring semantics, nesting, propagation,
shipping, and the merge/analysis tools.

Covers the r12 acceptance points: trace-context propagation across a REAL
gRPC round trip, ring-buffer overwrite-oldest under concurrent writers,
nested-span self-time agreeing with PhaseTimers on the same block, and
trace_dump merging two worker processes with skewed clocks.
"""

import json
import threading
import time

import pytest

from elasticdl_tpu.common import trace
from elasticdl_tpu.common.metrics import PhaseTimers
from elasticdl_tpu.common.trace import TraceRecorder


@pytest.fixture()
def recorder():
    """Enable the PROCESS recorder for a test, restoring state after (the
    module helpers and PhaseTimers read the global)."""
    was = trace.enabled()
    rec = trace.configure(enabled=True, capacity=4096)
    rec.clear()
    yield rec
    rec.clear()
    trace.configure(enabled=was)


# ---------------------------------------------------------------- recorder


def test_disabled_recorder_is_noop():
    rec = TraceRecorder(enabled=False)
    with rec.span("x", cat="t"):
        pass
    rec.instant("y")
    assert rec.export() == []


def test_span_and_instant_shapes():
    rec = TraceRecorder(enabled=True, capacity=16)
    with rec.span("work", cat="phase", k=1):
        rec.instant("tick", cat="event", n=2)
    inst, span = rec.export()
    assert inst["ph"] == "i" and inst["name"] == "tick"
    assert inst["args"]["n"] == 2
    assert span["ph"] == "X" and span["name"] == "work"
    assert span["cat"] == "phase"
    assert span["dur"] >= 0
    assert span["args"]["k"] == 1
    assert span["args"]["span_id"] > 0
    # Timestamps are wall-anchored microseconds: the instant fired inside
    # the span's window.
    assert span["ts"] <= inst["ts"] <= span["ts"] + span["dur"] + 1


def test_span_parent_nesting():
    rec = TraceRecorder(enabled=True, capacity=16)
    with rec.span("outer") as outer:
        assert rec.current_span_id() == outer.span_id
        with rec.span("inner") as inner:
            assert inner.parent_id == outer.span_id
    inner_ev, outer_ev = rec.export()
    assert inner_ev["args"]["parent"] == outer_ev["args"]["span_id"]


def test_ring_overwrites_oldest_single_thread():
    rec = TraceRecorder(enabled=True, capacity=8)
    for i in range(20):
        rec.instant("e", i=i)
    kept = [e["args"]["i"] for e in rec.export()]
    assert kept == list(range(12, 20))  # the NEWEST window, in order
    assert rec.dropped > 0


def test_ring_overwrite_oldest_under_concurrent_writers():
    """N writers x M events into a capacity-C ring: the ring holds exactly
    C events, and each writer's surviving events are a SUFFIX of its own
    append sequence (overwrite-oldest means no writer's newer event is
    dropped while its older one survives)."""
    cap, writers, per = 256, 8, 400
    rec = TraceRecorder(enabled=True, capacity=cap)

    def _write(w):
        for i in range(per):
            rec.instant("e", w=w, i=i)

    threads = [
        threading.Thread(target=_write, args=(w,)) for w in range(writers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = rec.export()
    assert len(events) == cap
    by_writer = {}
    for e in events:
        by_writer.setdefault(e["args"]["w"], []).append(e["args"]["i"])
    for w, seq in by_writer.items():
        # In-order (deque append preserves per-thread order)...
        assert seq == sorted(seq), f"writer {w} out of order"
        # ...and a suffix: everything from its first survivor onward.
        assert seq == list(range(seq[0], per)), f"writer {w} not a suffix"


def test_drain_slice_bounded_and_fifo():
    rec = TraceRecorder(enabled=True, capacity=64)
    for i in range(10):
        rec.instant("e", i=i)
    first = rec.drain_slice(4)
    assert [e["args"]["i"] for e in first] == [0, 1, 2, 3]
    rest = rec.drain_slice(100)
    assert [e["args"]["i"] for e in rest] == [4, 5, 6, 7, 8, 9]
    assert rec.drain_slice(5) == []


# ------------------------------------------- PhaseTimers span integration


def test_phase_timers_emit_spans(recorder):
    timers = PhaseTimers()
    with timers.phase("prep_wait"):
        time.sleep(0.01)
    (ev,) = [e for e in recorder.export() if e["ph"] == "X"]
    assert ev["name"] == "prep_wait"
    assert ev["cat"] == "phase"
    assert ev["dur"] >= 9e3  # microseconds


def test_nested_span_self_time_agrees_with_phase_timers(recorder):
    """The trace side computes per-span SELF time with its own per-thread
    stack; PhaseTimers computes per-phase self time with ITS stack.  On a
    nested block the two independent implementations must agree."""
    timers = PhaseTimers()
    with timers.phase("control"):
        time.sleep(0.02)
        with timers.phase("lease_wait"):
            time.sleep(0.03)
        time.sleep(0.01)
    snap = timers.snapshot()
    self_us = {}
    for e in recorder.export():
        if e["ph"] == "X" and e["cat"] == "phase":
            self_us[e["name"]] = (
                self_us.get(e["name"], 0.0) + e["args"]["self_us"]
            )
    assert set(self_us) == {"control", "lease_wait"}
    for name in self_us:
        # Tolerance: the two stacks bracket each other's bookkeeping by a
        # few calls of overhead per nesting level.
        assert self_us[name] / 1e6 == pytest.approx(snap[name], abs=5e-3)
    # And the decomposition really is a partition: control's self time
    # excludes the nested lease_wait.
    assert self_us["control"] / 1e6 < 0.045


# ------------------------------------------------- gRPC round-trip context


def test_trace_context_propagates_over_real_grpc(recorder):
    """Client span id rides the request envelope; the servicer's rpc.server
    span names it as remote_parent — one logical RPC, linked across the
    wire."""
    from elasticdl_tpu.common.rpc import JsonRpcClient
    from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    servicer = MasterServicer(TaskDispatcher([]))
    server = MasterServer(servicer, port=0).start()
    client = JsonRpcClient(server.address)
    try:
        client.wait_ready(10.0)
        client.call("RegisterWorker", {"worker_id": "w0"})
        recorder.clear()
        resp = client.call("Heartbeat", {"worker_id": "w0"})
        assert resp.get("server_ts_us") is not None
        events = recorder.export()
        client_spans = [
            e for e in events
            if e["ph"] == "X" and e["cat"] == "rpc.client"
            and e["name"] == "rpc:Heartbeat"
        ]
        server_spans = [
            e for e in events
            if e["ph"] == "X" and e["cat"] == "rpc.server"
            and e["name"] == "rpc:Heartbeat"
        ]
        assert len(client_spans) == 1 and len(server_spans) == 1
        assert (
            server_spans[0]["args"]["remote_parent"]
            == client_spans[0]["args"]["span_id"]
        )
        assert client_spans[0]["args"]["deadline_s"] == 30.0
        # The server span nests INSIDE the client span's window (same
        # process here, so no clock alignment needed).
        cs, ss = client_spans[0], server_spans[0]
        assert cs["ts"] <= ss["ts"]
        assert ss["ts"] + ss["dur"] <= cs["ts"] + cs["dur"] + 1
    finally:
        client.close()
        server.stop()


def test_heartbeat_slice_shipping_and_dump(recorder):
    """Worker-shipped slices land in the master's per-worker buffer and
    come back out of DumpTrace; shipping DRAINS the worker ring."""
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    servicer = MasterServicer(TaskDispatcher([]))
    servicer.RegisterWorker({"worker_id": "w0"})
    recorder.clear()
    recorder.instant("e", i=1)
    recorder.instant("e", i=2)
    events = recorder.drain_slice(512)
    assert recorder.export() == []  # drained
    servicer.Heartbeat({
        "worker_id": "w0",
        "trace": {"events": events, "clock_offset_us": 123.0, "dropped": 0},
    })
    dump = servicer.DumpTrace({})
    proc = dump["processes"]["w0"]
    assert [e["args"]["i"] for e in proc["events"]] == [1, 2]
    assert proc["clock_offset_us"] == 123.0
    # Non-draining: a second dump sees the same window.
    assert len(servicer.DumpTrace({})["processes"]["w0"]["events"]) == 2


def test_departed_worker_trace_buffers_are_bounded(recorder):
    """Master-side rings of DEPARTED workers are retained (the job-end tail
    is dumped after workers exit) but capped at TRACE_DEPARTED_KEEP, most
    recently updated win — memory must track current world size, not
    historical membership."""
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    servicer = MasterServicer(TaskDispatcher([]))
    keep = MasterServicer.TRACE_DEPARTED_KEEP
    n = keep + 5
    for i in range(n):
        servicer.RegisterWorker({"worker_id": f"w{i}"})
        servicer.Heartbeat({
            "worker_id": f"w{i}",
            "trace": {"events": [{"ph": "i", "name": "e", "ts": float(i)}]},
        })
    # Everyone but w0 departs (w0 beat first = least recently updated of
    # the departed set).
    servicer._on_membership_change(2, ["w0"])
    with servicer._lock:
        held = set(servicer._trace_buffers)
    assert "w0" in held  # current member always kept
    assert len(held) <= keep + 1
    # The survivors among the departed are the most recently updated ones.
    assert f"w{n-1}" in held and "w1" not in held


def test_merge_skips_events_with_malformed_ts():
    from tools.trace_dump import merge

    dump = {
        "master_events": [
            {"ph": "i", "name": "ok", "ts": 5.0, "tid": 1},
            {"ph": "i", "name": "bad", "ts": None, "tid": 1},
            {"ph": "i", "name": "bad2", "ts": "later", "tid": 1},
            {"ph": "i", "name": "bad3", "ts": True, "tid": 1},
        ],
        "processes": {},
    }
    merged = merge(dump)
    names = [e["name"] for e in merged["traceEvents"] if e.get("ph") == "i"]
    assert names == ["ok"]


# ------------------------------------------------------- merge / analysis


def _mk_span(name, cat, ts, dur, tid=1, **args):
    return {
        "ph": "X", "name": name, "cat": cat, "ts": ts, "dur": dur,
        "tid": tid, "args": args,
    }


def test_trace_dump_merges_skewed_clocks(tmp_path):
    """Two worker processes with skewed clocks merge onto the master
    timeline: the same physical moment (each worker's gang boundary) lands
    at the same merged timestamp once each worker's RTT-midpoint offset is
    applied."""
    from tools.trace_dump import merge

    # Physical truth: both workers cross the gang boundary at master time
    # 1_000_000 us.  w0's clock runs 5 s behind the master, w1's 2 s ahead
    # -> their LOCAL timestamps differ by 7 s for the same moment.
    dump = {
        "master_events": [_mk_span("rpc:GetGroupTask", "rpc.server",
                                   1_000_000.0, 500.0)],
        "processes": {
            "w0": {
                "events": [_mk_span("gang_boundary", "gang",
                                    1_000_000.0 - 5_000_000.0, 400.0)],
                "clock_offset_us": 5_000_000.0,
                "dropped": 0,
            },
            "w1": {
                "events": [_mk_span("gang_boundary", "gang",
                                    1_000_000.0 + 2_000_000.0, 400.0)],
                "clock_offset_us": -2_000_000.0,
                "dropped": 0,
            },
        },
    }
    merged = merge(dump)
    spans = [
        e for e in merged["traceEvents"]
        if e.get("ph") == "X" and e["name"] == "gang_boundary"
    ]
    assert len(spans) == 2
    assert spans[0]["ts"] == pytest.approx(1_000_000.0)
    assert spans[1]["ts"] == pytest.approx(1_000_000.0)
    # Distinct integer pids with process_name metadata (Perfetto/Chrome
    # both load this shape).
    names = {
        e["pid"]: e["args"]["name"]
        for e in merged["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert set(names.values()) == {"master", "w0", "w1"}
    assert all(isinstance(p, int) for p in names)
    json.dumps(merged)  # the file must serialize as-is


def test_straggler_report_skew_and_phase_stats():
    """Per-rank gang wait totals, skew, straggler identification, and
    per-phase p50/p99 (+ shared histogram) from a merged trace."""
    from tools.straggler_report import analyze

    events = [
        {"ph": "M", "pid": 1, "tid": 0, "ts": 0, "name": "process_name",
         "args": {"name": "w0"}},
        {"ph": "M", "pid": 2, "tid": 0, "ts": 0, "name": "process_name",
         "args": {"name": "w1"}},
    ]
    # w0 is the straggler: long prep, short waits.  w1 waits on it: short
    # prep, long step_wait + gang_boundary.
    for i in range(4):
        t = i * 100_000.0
        events += [
            dict(_mk_span("prep_wait", "phase", t, 80_000.0), pid=1),
            dict(_mk_span("gang_boundary", "gang", t + 80_000, 1_000.0), pid=1),
            dict(_mk_span("step_wait", "phase", t + 81_000, 4_000.0), pid=1),
            dict(_mk_span("prep_wait", "phase", t, 10_000.0), pid=2),
            dict(_mk_span("gang_boundary", "gang", t + 10_000, 5_000.0), pid=2),
            dict(_mk_span("step_wait", "phase", t + 15_000, 70_000.0), pid=2),
        ]
    report = analyze({"traceEvents": events})
    skew = report["gang_boundary_skew"]
    assert skew["straggler"] == "w0"
    assert skew["per_rank"]["w0"]["total_ms"] == pytest.approx(20.0)
    assert skew["per_rank"]["w1"]["total_ms"] == pytest.approx(300.0)
    assert skew["skew_ms"] == pytest.approx(280.0)
    w0 = report["processes"]["w0"]["phases"]
    assert w0["prep_wait"]["count"] == 4
    assert w0["prep_wait"]["p50_ms"] == pytest.approx(80.0)
    assert w0["prep_wait"]["p99_ms"] == pytest.approx(80.0)
    # The shared histogram grid rode along (tail shape, not just points).
    hist = w0["prep_wait"]["hist"]
    assert sum(hist["counts"]) == 4
    assert len(hist["counts"]) == len(hist["edges_ms"]) + 1


def test_latency_stats_histogram_buckets():
    from tools.artifact import DEFAULT_BUCKET_EDGES_MS, latency_stats

    out = latency_stats([0.05, 0.3, 3.0, 3.0, 40.0, 99999.0], buckets=True)
    hist = out["hist"]
    assert hist["edges_ms"] == list(DEFAULT_BUCKET_EDGES_MS)
    counts = hist["counts"]
    assert sum(counts) == 6
    assert counts[0] == 1          # 0.05 under the first edge
    assert counts[-1] == 1         # 99999 overflow
    edges = hist["edges_ms"]
    assert counts[edges.index(0.5)] == 1      # 0.3 in (0.2, 0.5]
    assert counts[edges.index(5.0)] == 2      # both 3.0s in (2, 5]
    assert counts[edges.index(50.0)] == 1     # 40 in (20, 50]
    assert latency_stats([], buckets=True) == {}
    # Explicit edges pass through.
    out = latency_stats([1.5], buckets=(1.0, 2.0))
    assert out["hist"]["edges_ms"] == [1.0, 2.0]
    assert out["hist"]["counts"] == [0, 1, 0]
