"""graftchaos (chaos/inject.py): plan grammar, injector semantics, the
hook points in the RPC client and the worker, standby-pool visibility, and
exactly-once task accounting across back-to-back pod kills."""

import threading
import time

import pytest

from elasticdl_tpu import chaos
from elasticdl_tpu.chaos.inject import (
    ChaosError,
    ChaosFault,
    ChaosInjector,
    ChaosRpcDropped,
    parse_plan,
)
from elasticdl_tpu.common import trace


@pytest.fixture(autouse=True)
def _reset_chaos_and_trace():
    """Chaos and trace are process-global; every test leaves them off."""
    yield
    chaos.configure("")
    chaos.set_context(rank=None, worker_id=None)
    trace.configure(enabled=False)
    trace.default().clear()


# ---------------------------------------------------------------------------
# plan grammar
# ---------------------------------------------------------------------------

class TestParsePlan:
    def test_full_grammar(self):
        plan = parse_plan(
            "kill:rank=1,step=4;"
            "stall:rank=0,point=prep,step=2,ms=500,count=2;"
            "delay_rpc:method=GetTask,ms=100,count=3,skip=5;"
            "drop_rpc:method=Heartbeat;"
            "delay_ps:ms=50,count=0"
        )
        kinds = [f.kind for f in plan]
        assert kinds == ["kill", "stall", "delay_rpc", "drop_rpc", "delay_ps"]
        assert plan[0].rank == 1 and plan[0].step == 4
        assert plan[1].point == "prep" and plan[1].ms == 500.0
        assert plan[2].skip == 5 and plan[2].count == 3
        assert plan[4].count == 0  # unlimited

    def test_empty_is_empty(self):
        assert parse_plan("") == []
        assert parse_plan(" ; ") == []

    @pytest.mark.parametrize("bad", [
        "zap:ms=1",                   # unknown kind
        "stall:rank=0",               # stall without ms
        "delay_rpc:method=GetTask",   # delay without ms
        "stall:ms=5,point=flush",     # unknown point
        "kill:frequency=9",           # unknown key
        "kill:rank",                  # malformed arg
        # kind-inapplicable keys: these parse into match conditions no
        # hook context can satisfy — a fault that silently never fires.
        "stall:rank=0,ms=5,method=GetTask",   # method= is rpc-only
        "delay_rpc:point=prep,ms=100",        # point= is stall-only
        "kill:rank=0,ms=9",                   # a kill has no duration
        "delay_ps:ms=5,rank=0",               # PS shard has no rank
    ])
    def test_malformed_plans_fail_loud(self, bad):
        with pytest.raises(ChaosError):
            parse_plan(bad)

    def test_kill_target_master(self):
        """r18: kill:target=master binds to the servicer's report hook
        and ONLY it — a plan can never kill both process families."""
        (f,) = parse_plan("kill:target=master,step=3")
        assert f.target == "master"
        assert f.matches("master:report", {"step": 3})
        assert not f.matches("master:report", {"step": 2})
        assert not f.matches("worker:task", {"step": 3, "rank": 0})
        # The default target stays the worker boundary.
        (g,) = parse_plan("kill:rank=0,step=1")
        assert not g.matches("master:report", {"step": 5})
        assert g.matches("worker:task", {"step": 1, "rank": 0})

    @pytest.mark.parametrize("bad", [
        "kill:target=ps,step=1",            # unknown target
        "kill:target=master,rank=1",        # master has no rank
        "kill:target=master,worker=w-0",    # ...nor a worker id
        "stall:target=master,ms=5",         # target is kill-only
    ])
    def test_master_target_misuse_fails_loud(self, bad):
        with pytest.raises(ChaosError):
            parse_plan(bad)

    def test_config_validates_plan(self):
        from elasticdl_tpu.common.config import JobConfig

        JobConfig(chaos="kill:rank=0,step=1").validate()
        JobConfig(chaos="kill:target=master,step=2").validate()
        with pytest.raises(ChaosError):
            JobConfig(chaos="zap:ms=1").validate()

    def test_config_roundtrips_chaos_knobs(self):
        from elasticdl_tpu.common.config import JobConfig

        c = JobConfig(
            chaos="stall:ms=5", gang_deadline_ms=250.0, gang_skip_budget=1
        )
        c2 = JobConfig.from_json(c.to_json())
        assert (c2.chaos, c2.gang_deadline_ms, c2.gang_skip_budget) == (
            "stall:ms=5", 250.0, 1
        )
        with pytest.raises(ValueError):
            JobConfig(gang_deadline_ms=-1).validate()


# ---------------------------------------------------------------------------
# injector semantics
# ---------------------------------------------------------------------------

class TestInjector:
    def test_disabled_is_noop(self):
        inj = ChaosInjector()
        assert not inj.enabled
        inj.fire("worker:task", {"rank": 0, "step": 99})  # nothing armed

    def test_module_hook_disabled_costs_one_check(self):
        # The module helper returns before touching the injector at all.
        chaos.configure("")
        assert not chaos.enabled()
        chaos.hook("worker:task", rank=0, step=10**9)

    def test_step_and_rank_gate(self):
        fired = []
        inj = ChaosInjector(parse_plan("kill:rank=1,step=4"))
        inj._exit = staticmethod(lambda code: fired.append(code))
        inj.set_context(rank=0)
        inj.fire("worker:task", {"step": 10})
        assert fired == []  # wrong rank
        inj.set_context(rank=1)
        inj.fire("worker:task", {"step": 3})
        assert fired == []  # step not reached
        inj.fire("worker:task", {"step": 4})
        assert fired == [chaos.CHAOS_KILL_EXIT_CODE]
        inj.fire("worker:task", {"step": 5})
        assert fired == [chaos.CHAOS_KILL_EXIT_CODE]  # count=1: once

    def test_worker_id_gate_survives_relaunch_names(self):
        """worker= matches the exact id, so a relaunched incarnation
        (-rN suffix) does NOT re-match — an injected kill cannot
        crash-loop its own replacement."""
        fired = []
        inj = ChaosInjector(parse_plan("kill:worker=job-worker-1,step=1"))
        inj._exit = staticmethod(lambda code: fired.append(code))
        inj.set_context(worker_id="job-worker-1-r1")
        inj.fire("worker:task", {"step": 5})
        assert fired == []
        inj.set_context(worker_id="job-worker-1")
        inj.fire("worker:task", {"step": 5})
        assert fired == [chaos.CHAOS_KILL_EXIT_CODE]

    def test_skip_then_count_window(self):
        inj = ChaosInjector(parse_plan("drop_rpc:method=Heartbeat,count=2,skip=1"))
        inj.fire("rpc:client", {"method": "GetTask"})  # method mismatch
        inj.fire("rpc:client", {"method": "Heartbeat"})  # skipped occurrence
        with pytest.raises(ChaosRpcDropped):
            inj.fire("rpc:client", {"method": "Heartbeat"})
        with pytest.raises(ChaosRpcDropped):
            inj.fire("rpc:client", {"method": "Heartbeat"})
        inj.fire("rpc:client", {"method": "Heartbeat"})  # budget exhausted
        stats = inj.stats()
        assert stats[0]["seen"] == 4 and stats[0]["fired"] == 2

    def test_stall_sleeps_and_point_binds(self):
        inj = ChaosInjector(parse_plan("stall:point=prep,ms=30,count=1"))
        t0 = time.perf_counter()
        inj.fire("worker:step", {})  # wrong point: no stall
        assert time.perf_counter() - t0 < 0.02
        t0 = time.perf_counter()
        inj.fire("worker:prep", {})
        assert time.perf_counter() - t0 >= 0.025

    def test_fired_fault_emits_chaos_instant(self):
        trace.configure(enabled=True)
        trace.default().clear()
        inj = ChaosInjector(parse_plan("delay_ps:ms=1"))
        inj.fire("ps:pull", {"table": "t"})
        events = trace.default().export()
        names = [e["name"] for e in events]
        assert "chaos:delay_ps" in names
        ev = events[names.index("chaos:delay_ps")]
        assert ev["cat"] == "chaos" and ev["args"]["point"] == "ps:pull"

    def test_configure_rearms_and_resets_state(self):
        chaos.configure("delay_ps:ms=1,count=1")
        assert chaos.enabled()
        chaos.hook("ps:pull")
        assert chaos.default().stats()[0]["fired"] == 1
        chaos.configure("delay_ps:ms=1,count=1")
        assert chaos.default().stats()[0]["fired"] == 0
        chaos.configure("")
        assert not chaos.enabled()


# ---------------------------------------------------------------------------
# the rpc:client hook over a REAL gRPC round trip
# ---------------------------------------------------------------------------

def test_rpc_client_drop_and_delay_inject(devices):
    from elasticdl_tpu.common.rpc import JsonRpcClient
    from elasticdl_tpu.data.reader import Shard
    from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    dispatcher = TaskDispatcher([Shard("f", 0, 10)])
    server = MasterServer(MasterServicer(dispatcher), port=0).start()
    client = JsonRpcClient(server.address)
    try:
        client.wait_ready(10.0)
        chaos.configure(
            "drop_rpc:method=Heartbeat,count=1;"
            "delay_rpc:method=GetMembership,ms=40,count=1"
        )
        client.call("RegisterWorker", {"worker_id": "w0"})  # unmatched
        with pytest.raises(ChaosRpcDropped):
            client.call("Heartbeat", {"worker_id": "w0"})
        # The drop budget is spent: the next beat goes through.
        assert "version" in client.call("Heartbeat", {"worker_id": "w0"})
        t0 = time.perf_counter()
        client.call("GetMembership", {})
        assert time.perf_counter() - t0 >= 0.035
    finally:
        chaos.configure("")
        client.close()
        server.stop()


# ---------------------------------------------------------------------------
# worker hook points: an in-process job under stall faults completes and
# the faults are attributable in the trace
# ---------------------------------------------------------------------------

def test_worker_job_completes_under_stall_faults(tmp_path, devices):
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.data.synthetic import generate
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker

    train = str(tmp_path / "train.rio")
    generate("mnist", train, 96)
    config = JobConfig(
        model_def="mnist.model_spec",
        training_data=train,
        minibatch_size=16,
        num_minibatches_per_task=2,
        trace=True,
        chaos="stall:point=task,ms=10,count=2;stall:point=prep,ms=5,count=1",
    )
    reader = create_data_reader(train)
    dispatcher = TaskDispatcher(reader.create_shards(32))
    servicer = MasterServicer(dispatcher)
    spec = load_model_spec(
        "elasticdl_tpu.models", "mnist.model_spec", compute_dtype="float32"
    )
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices,
    )
    result = worker.run()
    assert result["tasks_done"] == 3 and servicer.dispatcher.finished()
    status = servicer.JobStatus({})
    assert status["duplicate_done"] == 0 and status["skipped"] == 0
    # The injected stalls are attributable: the worker drained its ring
    # into the heartbeat/report channel, so the chaos:stall instants sit
    # in the master's banked per-worker buffer (plus any undrained tail).
    dump = servicer.DumpTrace({})
    names = [
        e["name"]
        for e in dump["processes"].get("w0", {}).get("events", [])
    ] + [e["name"] for e in trace.default().export()]
    assert names.count("chaos:stall") == 3


# ---------------------------------------------------------------------------
# standby-pool depth rides Heartbeat and JobStatus
# ---------------------------------------------------------------------------

def test_standby_depth_rides_heartbeat_and_job_status():
    from elasticdl_tpu.data.reader import Shard
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    servicer = MasterServicer(TaskDispatcher([Shard("f", 0, 10)]))
    servicer.RegisterWorker({"worker_id": "w0"})
    resp = servicer.Heartbeat({"worker_id": "w0"})
    assert "standby_pool" not in resp  # no pool wired: absent, not 0
    depth = {"n": 1}
    servicer.set_standby_depth(lambda: depth["n"])
    assert servicer.Heartbeat({"worker_id": "w0"})["standby_pool"] == 1
    depth["n"] = 0  # drained pool is VISIBLE before the next failure
    assert servicer.Heartbeat({"worker_id": "w0"})["standby_pool"] == 0
    assert servicer.JobStatus({})["standby_pool"] == 0
    servicer.set_standby_depth(lambda: None)  # backend without a pool
    assert "standby_pool" not in servicer.Heartbeat({"worker_id": "w0"})


def test_pod_manager_standby_depth_delegates():
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.master.pod_manager import (
        FakePodBackend,
        PodManager,
        ProcessPodBackend,
    )

    config = JobConfig()
    assert PodManager(FakePodBackend(), config).standby_depth() is None
    cold = ProcessPodBackend(warm_standby=False)
    assert PodManager(cold, config).standby_depth() is None
    warm = ProcessPodBackend(warm_standby=True)
    assert PodManager(warm, config).standby_depth() == 0  # pool not filled yet


# ---------------------------------------------------------------------------
# exactly-once accounting across back-to-back kills (FakePodBackend fleet)
# ---------------------------------------------------------------------------

def test_exactly_once_accounting_across_two_pod_kills():
    """Kill two ranks back-to-back: each dead worker's in-flight tasks
    requeue exactly once through the membership cascade, every task
    reports done exactly once, and the duplicate-done counter stays 0."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.data.reader import Shard
    from elasticdl_tpu.master.pod_manager import FakePodBackend, PodManager
    from elasticdl_tpu.master.rendezvous import RendezvousServer
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    shards = [Shard("f", i * 10, (i + 1) * 10) for i in range(6)]
    dispatcher = TaskDispatcher(shards)
    rendezvous = RendezvousServer(heartbeat_timeout_s=60.0)
    servicer = MasterServicer(dispatcher, rendezvous=rendezvous)
    backend = FakePodBackend()
    manager = PodManager(
        backend,
        JobConfig(num_workers=2, relaunch_on_worker_failure=True),
    )
    manager.add_listener(
        lambda name, phase: rendezvous.remove(name)
        if phase in ("Failed", "Succeeded", "Deleted") else None
    )
    manager.start(2)
    pods = sorted(backend.pods)
    for pod in pods:
        servicer.RegisterWorker({"worker_id": pod})

    # Each worker leases two tasks; one task per worker completes before
    # the kills, the other is in flight when its worker dies.
    leases = {pod: servicer.GetTask({"worker_id": pod, "lease": 2}) for pod in pods}
    done_ids = []
    for pod in pods:
        first = leases[pod]["tasks"][0]
        servicer.ReportTaskResult({
            "worker_id": pod, "task_id": first["task_id"],
            "task_type": "training", "success": True,
        })
        done_ids.append(first["task_id"])

    in_flight = {
        pod: leases[pod]["tasks"][1]["task_id"] for pod in pods
    }
    backend.fail_pod(pods[0])  # first kill: splice path would adopt a spare
    backend.fail_pod(pods[1])  # second, back-to-back
    # Both dead workers' in-flight tasks are back in todo exactly once.
    counts = dispatcher.counts()
    assert counts["doing"] == 0 and counts["todo"] == 4

    # A LATE success from a dead worker is rejected AND counted: its task
    # already requeued, so accepting it would double-train the shard.
    resp = servicer.ReportTaskResult({
        "worker_id": pods[0], "task_id": in_flight[pods[0]],
        "task_type": "training", "success": True,
    })
    assert resp["accepted"] is False
    assert dispatcher.counts()["duplicate_done"] == 1

    # The relaunched incarnations drain the queue; accounting stays exact.
    survivors = [n for n in manager.live_pods()]
    assert len(survivors) == 2 and set(survivors) != set(pods)
    for pod in survivors:
        servicer.RegisterWorker({"worker_id": pod})
    while True:
        resp = servicer.GetTask({"worker_id": survivors[0]})
        if resp["task"] is None:
            break
        servicer.ReportTaskResult({
            "worker_id": survivors[0], "task_id": resp["task"]["task_id"],
            "task_type": "training", "success": True,
        })
    final = dispatcher.counts()
    assert final["finished"] and final["done"] == 6
    # done == shards: the requeued tasks trained once each; the one late
    # duplicate stayed rejected.
    assert final["duplicate_done"] == 1 and final["abandoned"] == 0
